"""Program-analysis passes over the engine's jitted programs.

Each pass consumes a :class:`ProgramArtifact` — a lazily traced / lowered /
compiled view of one named engine program, rebuilt from the abstract call
signature the compile telemetry captured at the program's cold dispatch —
and returns a :class:`PassResult` of violations + a machine-readable
summary. The properties the passes check are exactly the runtime guarantees
the engine claims (PR 1/2 asserted them ad hoc per test):

* ``donation``    — every declared donated argument is honored as an
  input/output alias in the compiled executable; unhonored donations are
  reported with the bytes they double-buffer (ZeRO's "no second copy of the
  training state" invariant, statically).
* ``dtype_promotion`` — no f32 matmul/conv is reachable from bf16/fp16
  data through an upcast (master-weight and softmax-boundary math is
  allowlisted structurally: elementwise/reduction f32 is fine, and an
  ``exp`` clears the taint — softmax-in-f32 is deliberate numerics).
* ``host_transfer`` — no callback primitive in the jaxpr and no
  infeed/outfeed/send/recv/python-callback custom-call in the compiled
  module: a hot-loop program must never bounce through the host.
* ``collectives``  — the static communication schedule (count + payload
  bytes per all-reduce/all-gather/reduce-scatter/all-to-all/…): surfaced as
  a summary, and gated when a ``collective_budget_bytes`` is configured
  (EQuARX-style static comms budget).

Passes are registered in ``PROGRAM_PASSES``; ``analyze_program`` runs a
selection against one artifact.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from . import hlo as hlo_parse


class AnalysisError(RuntimeError):
    """Raised by ``analysis.verify: raise`` when a pass reports an
    error-severity violation on a freshly compiled engine program."""


@dataclass
class Violation:
    pass_name: str
    program: str
    message: str
    severity: str = "error"  # "error" | "warn"
    details: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "pass": self.pass_name,
            "program": self.program,
            "message": self.message,
            "severity": self.severity,
            "details": self.details,
        }


@dataclass
class PassResult:
    violations: List[Violation] = field(default_factory=list)
    summary: Dict[str, Any] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(v.severity == "error" for v in self.violations)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "violations": [v.as_dict() for v in self.violations],
            "summary": self.summary,
        }


class ProgramArtifact:
    """Lazily materialized views of one jitted program.

    ``trace`` → ``jaxpr`` (cheap, no XLA), ``lowered`` → ``args_info``
    (declared donations), ``compiled`` → optimized HLO text (honored
    aliases, SPMD collectives). Each stage is computed once and shared by
    every pass run against the artifact. Building from abstract
    ShapeDtypeStructs means no device buffer is touched; the cost of a full
    build is one extra trace + compile of the program.
    """

    def __init__(self, name: str, wrapper):
        self.name = name
        self._wrapper = wrapper
        self._traced = None
        self._lowered = None
        self._compiled = None
        self._hlo_text = None

    @property
    def traced(self):
        if self._traced is None:
            self._traced = self._wrapper.trace_abstract()
        return self._traced

    @property
    def jaxpr(self):
        return self.traced.jaxpr

    @property
    def lowered(self):
        if self._lowered is None:
            self._lowered = self.traced.lower()
        return self._lowered

    @property
    def flat_args_info(self) -> List[Any]:
        """Flattened ``jax.stages.ArgInfo`` list: ``.donated`` + shape/dtype
        per flat argument, in lowering parameter order."""
        return jax.tree_util.tree_leaves(self.lowered.args_info)

    @property
    def compiled(self):
        if self._compiled is None:
            self._compiled = self.lowered.compile()
        return self._compiled

    @property
    def hlo_text(self) -> str:
        if self._hlo_text is None:
            self._hlo_text = self.compiled.as_text()
        return self._hlo_text


def _arg_bytes(info) -> int:
    n = 1
    for d in getattr(info, "shape", ()):  # global logical bytes
        n *= int(d)
    try:
        import numpy as np

        return n * int(np.dtype(info.dtype).itemsize)
    except Exception:
        return n * 4


# ---------------------------------------------------------------------------
# donation-aliasing verifier
# ---------------------------------------------------------------------------
def donation_pass(art: ProgramArtifact, config: Optional[Dict[str, Any]] = None) -> PassResult:
    cfg = config or {}
    min_bytes = int(cfg.get("min_donation_bytes", 0))
    res = PassResult()
    infos = art.flat_args_info
    donated_idx = [i for i, a in enumerate(infos) if getattr(a, "donated", False)]
    aliased = hlo_parse.parse_input_output_aliases(art.hlo_text)
    n_params = hlo_parse.entry_parameter_count(art.hlo_text)

    donated_bytes = sum(_arg_bytes(infos[i]) for i in donated_idx)
    res.summary = {
        "declared_donations": len(donated_idx),
        "declared_donated_bytes": donated_bytes,
        "aliased_params": len(aliased),
    }
    if not donated_idx:
        return res

    if not aliased and "input_output_alias" in hlo_parse.module_header(art.hlo_text):
        # the attribute EXISTS in the header but our regex extracted
        # nothing: XLA's text format drifted past the parser. Degrade to a
        # warning (hlo.py's best-effort contract) instead of failing a
        # verify=raise deployment on a parse artifact. (A header with NO
        # input_output_alias attribute is the real "nothing aliased"
        # signal — XLA omits the attribute when the table is empty — and
        # falls through to the hard violations below.)
        res.summary["alias_table"] = "present_but_unparseable"
        res.violations.append(
            Violation(
                "donation",
                art.name,
                f"{len(donated_idx)} donated args; an input_output_alias "
                "attribute exists in the compiled module header but could "
                "not be parsed — donation unverifiable (HLO text drift?)",
                severity="warn",
                details={"donated_bytes": donated_bytes},
            )
        )
        return res

    if n_params is not None and n_params != len(infos):
        # jit pruned unused arguments: flat index ↔ HLO parameter mapping is
        # gone. Fall back to an aggregate check so we still catch "nothing
        # got aliased" without mis-blaming a specific argument.
        res.summary["arg_pruning"] = {"flat_args": len(infos), "hlo_params": n_params}
        if not aliased:
            res.violations.append(
                Violation(
                    "donation",
                    art.name,
                    f"{len(donated_idx)} donated args but the compiled module "
                    "aliases none of its parameters — the whole donated state "
                    f"(~{donated_bytes} bytes) is double-buffered",
                    severity="error" if donated_bytes >= min_bytes else "warn",
                    details={"donated_bytes": donated_bytes},
                )
            )
        elif len(aliased) < len(donated_idx):
            # some donations went unhonored but the pruned index mapping
            # cannot name which: report the shortfall rather than letting a
            # partial regression read as fully verified
            res.violations.append(
                Violation(
                    "donation",
                    art.name,
                    f"only {len(aliased)} of {len(donated_idx)} donated args "
                    "are aliased and argument pruning prevents per-arg "
                    "attribution — donation partially unverifiable",
                    severity="warn",
                    details={"aliased": len(aliased), "donated": len(donated_idx)},
                )
            )
        else:
            res.summary["alias_check"] = "aggregate_only"  # pruned: counts match
        return res

    unhonored = [i for i in donated_idx if i not in aliased]
    wasted = sum(_arg_bytes(infos[i]) for i in unhonored)
    res.summary["unhonored"] = len(unhonored)
    res.summary["double_buffered_bytes"] = wasted
    for i in unhonored:
        info = infos[i]
        b = _arg_bytes(info)
        sev = "error" if b >= min_bytes else "warn"
        res.violations.append(
            Violation(
                "donation",
                art.name,
                f"donated arg {i} ({getattr(info, 'dtype', '?')}"
                f"{list(getattr(info, 'shape', ()))}) is not aliased in the "
                f"compiled module: {b} bytes double-buffered",
                severity=sev,
                details={"arg_index": i, "bytes": b},
            )
        )
    return res


# ---------------------------------------------------------------------------
# jaxpr walking helpers (shared by dtype audit, host-transfer, shape scan)
# ---------------------------------------------------------------------------
def _sub_jaxprs(eqn) -> List[Any]:
    """Every jaxpr-valued param of an equation (pjit/scan/while/cond/
    custom_* call bodies), as ClosedJaxpr-or-Jaxpr objects."""
    subs = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for item in vals:
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):  # ClosedJaxpr
                subs.append(item)
            elif hasattr(item, "eqns") and hasattr(item, "invars"):  # Jaxpr
                subs.append(item)
    return subs


def _as_jaxpr(j):
    return j.jaxpr if hasattr(j, "jaxpr") and hasattr(j, "consts") else j


def iter_eqns(jaxpr):
    """Depth-first iteration over every equation, including call/control-flow
    sub-jaxprs (the closed-over bodies GSPMD actually runs)."""
    j = _as_jaxpr(jaxpr)
    for eqn in j.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def find_aval_shapes(jaxpr, shape: Tuple[int, ...]) -> List[Tuple[str, Tuple[int, ...]]]:
    """Equations (recursively) whose output aval matches ``shape`` exactly —
    the structural "does this program materialize a tensor of this shape"
    probe (e.g. the banned NH-wide GQA cache copy)."""
    shape = tuple(shape)
    hits = []
    for eqn in iter_eqns(jaxpr):
        for var in eqn.outvars:
            s = tuple(getattr(getattr(var, "aval", None), "shape", ()) or ())
            if s == shape:
                hits.append((str(eqn.primitive), s))
    return hits


def _src(eqn) -> str:
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return f"{frame.file_name}:{frame.start_line}"
    except Exception:
        pass
    return ""


# ---------------------------------------------------------------------------
# dtype-promotion audit
# ---------------------------------------------------------------------------
_LOW_DTYPES = ("bfloat16", "float16")
_COMPUTE_PRIMS = {"dot_general", "conv_general_dilated"}
# numerics boundaries: an exp/sigmoid output is a softmax-style probability,
# deliberately computed in f32 — data flowing through it stops being "an
# upcast copy of low-precision values"
_TAINT_BOUNDARY_PRIMS = {"exp", "logistic", "erf"}


def _dtype_of(var) -> str:
    return str(getattr(getattr(var, "aval", None), "dtype", ""))


def _dtype_walk(jaxpr, tainted_in: set, violations: List[Violation], program: str) -> set:
    """Propagate "f32 upcast of low-precision data" taint through one jaxpr.
    ``tainted_in``: ids of tainted invars. Returns ids of tainted outvars."""
    j = _as_jaxpr(jaxpr)
    tainted = set(tainted_in)

    def is_tainted(v):
        return id(v) in tainted

    def is_low(v):
        return _dtype_of(v) in _LOW_DTYPES

    for eqn in j.eqns:
        prim = str(eqn.primitive)
        subs = _sub_jaxprs(eqn)
        if subs:
            # map outer taint positionally into each body (offset from the
            # end: pjit aligns exactly, cond skips the index operand, scan
            # aligns consts+carry+xs) and taint the eqn outputs from the
            # union of body outvar taints (offset from the end again)
            out_taint: set = set()
            for sub in subs:
                sj = _as_jaxpr(sub)
                off = len(eqn.invars) - len(sj.invars)
                sub_in = set()
                for i, sv in enumerate(sj.invars):
                    outer_i = i + off
                    if 0 <= outer_i < len(eqn.invars):
                        ov = eqn.invars[outer_i]
                        if is_tainted(ov):
                            sub_in.add(id(sv))
                sub_out = _dtype_walk(sub, sub_in, violations, program)
                ooff = len(eqn.outvars) - len(sj.outvars)
                for i, sv in enumerate(sj.outvars):
                    outer_i = i + ooff
                    if id(sv) in sub_out and 0 <= outer_i < len(eqn.outvars):
                        out_taint.add(id(eqn.outvars[outer_i]))
            tainted |= out_taint
            continue

        any_tainted_in = any(is_tainted(v) for v in eqn.invars if hasattr(v, "aval"))

        if prim == "convert_element_type":
            (inv,) = [v for v in eqn.invars if hasattr(v, "aval")][:1] or [None]
            outv = eqn.outvars[0]
            if inv is not None and _dtype_of(outv) == "float32" and (
                is_low(inv) or is_tainted(inv)
            ):
                tainted.add(id(outv))
            continue

        if prim in _COMPUTE_PRIMS:
            outv = eqn.outvars[0]
            if _dtype_of(outv) == "float32" and any_tainted_in:
                violations.append(
                    Violation(
                        "dtype_promotion",
                        program,
                        f"f32 {prim} consumes an upcast of bf16/fp16 data "
                        f"({_src(eqn) or 'source unknown'}): compute runs in "
                        "full precision where the model stores half precision",
                        details={"primitive": prim, "source": _src(eqn)},
                    )
                )
                tainted.add(id(outv))
            continue

        if prim in _TAINT_BOUNDARY_PRIMS:
            continue  # outputs are deliberate-f32 numerics, not upcast copies

        if any_tainted_in:
            for outv in eqn.outvars:
                if _dtype_of(outv) == "float32":
                    tainted.add(id(outv))

    return {id(v) for v in j.outvars if id(v) in tainted}


def dtype_promotion_pass(
    art: ProgramArtifact, config: Optional[Dict[str, Any]] = None
) -> PassResult:
    res = PassResult()
    jaxpr = art.jaxpr
    violations: List[Violation] = []
    _dtype_walk(jaxpr, set(), violations, art.name)
    # duplicate sites collapse to one violation per (prim, source)
    seen = set()
    for v in violations:
        key = (v.details.get("primitive"), v.details.get("source"))
        if key in seen:
            continue
        seen.add(key)
        res.violations.append(v)
    low_inputs = sum(
        1 for v in _as_jaxpr(jaxpr).invars if _dtype_of(v) in _LOW_DTYPES
    )
    res.summary = {"low_precision_inputs": low_inputs, "f32_upcast_compute_sites": len(res.violations)}
    return res


# ---------------------------------------------------------------------------
# host-transfer detector
# ---------------------------------------------------------------------------
_CALLBACK_PRIMS = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "outside_call",
    "host_callback_call",
}


def host_transfer_pass(
    art: ProgramArtifact, config: Optional[Dict[str, Any]] = None
) -> PassResult:
    res = PassResult()
    jaxpr_hits = []
    for eqn in iter_eqns(art.jaxpr):
        prim = str(eqn.primitive)
        if prim in _CALLBACK_PRIMS or prim == "debug_print":
            jaxpr_hits.append({"primitive": prim, "source": _src(eqn)})
    hlo_hits = hlo_parse.find_host_ops(art.hlo_text)
    for h in jaxpr_hits:
        res.violations.append(
            Violation(
                "host_transfer",
                art.name,
                f"host callback primitive {h['primitive']} inside a jitted "
                f"hot-loop program ({h['source'] or 'source unknown'}): every "
                "dispatch round-trips through python",
                details=h,
            )
        )
    # HLO hits: callback custom-calls are the lowered form of the jaxpr
    # callbacks already reported above (suppress those when a jaxpr hit
    # explains them); raw host-boundary ops (infeed/outfeed/send/recv) are
    # ALWAYS violations of their own — a callback elsewhere in the program
    # must not mask them
    for h in hlo_hits:
        is_callback_lowering = h["op"].startswith("custom-call:")
        if is_callback_lowering and jaxpr_hits:
            continue
        res.violations.append(
            Violation(
                "host_transfer",
                art.name,
                f"host-boundary op {h['op']} in the compiled module "
                f"(jax op: {h['jax_op'] or 'unknown'})",
                details=h,
            )
        )
    res.summary = {"jaxpr_callbacks": len(jaxpr_hits), "hlo_host_ops": len(hlo_hits)}
    return res


# ---------------------------------------------------------------------------
# collective schedule extractor
# ---------------------------------------------------------------------------
def collectives_pass(
    art: ProgramArtifact, config: Optional[Dict[str, Any]] = None
) -> PassResult:
    cfg = config or {}
    budget = cfg.get("collective_budget_bytes")
    res = PassResult()
    # ONE line scan: the per-occurrence detail records carry the same
    # payload-byte accounting collect_collectives defined, so the legacy
    # per-op aggregate folds out of them instead of re-parsing the module
    details = hlo_parse.collect_collective_details(art.hlo_text)
    # per-op-kind wire + quantized breakdown (ISSUE 20: the MoE dispatch/
    # combine all-to-alls get the same dtype-aware pricing the quantized
    # all-reduces got — the green gate reads ops["all-to-all"]["quantized"]
    # to assert the int8 arm's wire bytes are exactly fp/4)
    ops: Dict[str, Dict[str, Any]] = {}
    for d in details:
        rec = ops.setdefault(
            d["op"],
            {
                "count": 0,
                "bytes": 0,
                "wire_bytes": 0.0,
                "quantized": {
                    "count": 0,
                    "bytes": 0,
                    "wire_bytes": 0.0,
                    "fp_equiv_wire_bytes": 0.0,
                },
            },
        )
        rec["count"] += 1
        rec["bytes"] += d["bytes"]
        rec["wire_bytes"] += d["wire_bytes"]
        if d["quantized_bytes"]:
            q = rec["quantized"]
            q["count"] += 1
            q["bytes"] += d["quantized_bytes"]
            q["wire_bytes"] += d["quantized_wire_bytes"]
            q["fp_equiv_wire_bytes"] += d["fp_equiv_wire_bytes"]
    for rec in ops.values():
        rec["wire_bytes"] = int(round(rec["wire_bytes"]))
        rec["quantized"]["wire_bytes"] = int(round(rec["quantized"]["wire_bytes"]))
        rec["quantized"]["fp_equiv_wire_bytes"] = int(
            round(rec["quantized"]["fp_equiv_wire_bytes"])
        )
    total_bytes = sum(r["bytes"] for r in ops.values())
    total_count = sum(r["count"] for r in ops.values())
    res.summary = {"ops": ops, "total_bytes": total_bytes, "total_count": total_count}
    # dtype-aware wire accounting (ISSUE 13: quantized TP comms): the ring
    # cost model per occurrence, with int8/f8 payloads — the EQuARX-style
    # quantized all-reduce exchanges — isolated and priced against their
    # fp32 equivalent. Bytes on the wire reflect the QUANTIZED dtype; the
    # fp_equiv comparison is exact (2·(g-1)/g·N int8 vs ·4N fp bytes = /4).
    wire_total = sum(d["wire_bytes"] for d in details)
    q_count = sum(1 for d in details if d["quantized_bytes"])
    q_bytes = sum(d["quantized_bytes"] for d in details)
    q_wire = sum(d["quantized_wire_bytes"] for d in details)
    q_fp_wire = sum(d["fp_equiv_wire_bytes"] for d in details)
    res.summary["wire_bytes"] = int(round(wire_total))
    res.summary["quantized"] = {
        "count": q_count,
        "bytes": q_bytes,
        "wire_bytes": int(round(q_wire)),
        "fp_equiv_wire_bytes": int(round(q_fp_wire)),
        "wire_reduction": (q_fp_wire / q_wire) if q_wire else 0.0,
    }
    if budget is not None and total_bytes > int(budget):
        res.violations.append(
            Violation(
                "collectives",
                art.name,
                f"static collective payload {total_bytes} bytes/device exceeds "
                f"the configured budget {int(budget)}",
                details={"total_bytes": total_bytes, "budget": int(budget), "ops": ops},
            )
        )
    q_budget = cfg.get("quantized_budget_bytes")
    if q_budget is not None and q_wire > int(q_budget):
        res.violations.append(
            Violation(
                "collectives",
                art.name,
                f"quantized collective wire payload {int(round(q_wire))} "
                f"bytes/device exceeds the configured quantized budget "
                f"{int(q_budget)}",
                details={
                    "quantized_wire_bytes": int(round(q_wire)),
                    "budget": int(q_budget),
                },
            )
        )
    return res


# ---------------------------------------------------------------------------
# comm/compute overlap verifier
# ---------------------------------------------------------------------------
_REAL_COMPUTE_OPS = {"dot", "convolution"}


_CALLEE_REF_RE = re.compile(
    r"(?:calls|to_apply|body|condition|true_computation|false_computation|"
    r"branch_computations)=\{?%([\w.$-]+)"
)
_CALLEE_REF_LIST_RE = re.compile(
    r"branch_computations=\{([^}]*)\}"
)


def _callee_refs(attrs: str) -> set:
    refs = set(_CALLEE_REF_RE.findall(attrs))
    for m in _CALLEE_REF_LIST_RE.finditer(attrs):
        refs.update(re.findall(r"%([\w.$-]+)", m.group(1)))
    return refs


def _computation_callees(comps) -> Dict[str, set]:
    """{computation: called-computation names} (fusion ``calls=``, while
    bodies/conditions, conditional branches, ``to_apply=``) — the one
    regex walk over every instruction's attrs, shared by transitive loop
    membership and compute reachability so the two always agree."""
    return {
        cname: set().union(*[_callee_refs(i.attrs) for i in instrs])
        if instrs
        else set()
        for cname, instrs in comps.items()
    }


def _computations_with_compute(comps, callees: Dict[str, set]) -> set:
    """Computation names that (transitively, through ``callees``) contain a
    dot/convolution — the "real compute" a collective can hide behind.
    Elementwise fusions don't count: a schedule is only overlapped if there
    is MXU-shaped work to run during the DMA."""
    direct = {
        cname
        for cname, instrs in comps.items()
        if any(i.op in _REAL_COMPUTE_OPS for i in instrs)
    }
    # fixpoint: a computation calling a compute-bearing one counts too
    changed = True
    has = set(direct)
    while changed:
        changed = False
        for cname, refs in callees.items():
            if cname not in has and refs & has:
                has.add(cname)
                changed = True
    return has


def _is_real_compute(instr, compute_comps: set) -> bool:
    """dot/conv, or a fusion/conditional/while/call whose (transitive)
    callee computations contain one — a cond-wrapped attention block or a
    nested scan is schedulable work a collective can hide behind."""
    if instr.op in _REAL_COMPUTE_OPS:
        return True
    if instr.op in ("fusion", "conditional", "while", "call"):
        return bool(_callee_refs(instr.attrs) & compute_comps)
    return False


def _reach(start_names, succ) -> set:
    seen = set(start_names)
    frontier = list(start_names)
    while frontier:
        n = frontier.pop()
        for nxt in succ.get(n, ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return seen


def overlap_pass(art: ProgramArtifact, config: Optional[Dict[str, Any]] = None) -> PassResult:
    """Static comm/compute-overlap verifier over the compiled schedule.

    For every collective in the optimized module (the order is the schedule:
    post-optimization HLO is ``is_scheduled=true``):

    * async ``-start``/``-done`` pairs are **hidden** when real compute
      (dot/conv, incl. inside fusions) sits between start and done in
      schedule order without depending on the start — the latency-hiding
      scheduler actually separated them;
    * sync collectives (the CPU mesh, unscheduled backends) are **hidden**
      when the computation contains real compute with no dependency path to
      or from the collective — independent work the scheduler is free to
      overlap (the feasibility the pipelined gather/bucketed reduce create).

    ``overlap_verified`` means no collective inside a while-loop body (the
    scanned layer stack / microbatch loop — the hot path the pipeline owns)
    is exposed; entry-level tail collectives only count toward
    ``exposed_bytes``. Exposed loop collectives are warn-severity findings
    (error with ``require_overlap``)."""
    cfg = config or {}
    res = PassResult()
    comps, _entry = hlo_parse.parse_computations(art.hlo_text)
    bodies = hlo_parse.while_body_computations(art.hlo_text)
    # loop membership is TRANSITIVE: a computation called from a while body
    # (a cond branch, a to_apply/call target, a nested loop) executes once
    # per iteration too — a collective there is just as serialized as one
    # directly in the body, and missing it would false-green the verifier
    callees = _computation_callees(comps)
    loop_comps = set(bodies)
    frontier = list(bodies)
    while frontier:
        c = frontier.pop()
        for ref in callees.get(c, ()):
            if ref not in loop_comps:
                loop_comps.add(ref)
                frontier.append(ref)
    compute_comps = _computations_with_compute(comps, callees)

    n_hidden = n_exposed = hidden_bytes = exposed_bytes = async_pairs = 0
    loop_total = 0
    # quantized loop collectives (the EQuARX exchanges of a quantized TP
    # serving program) verified hidden — the gate asserts the quantized
    # comm schedule was actually SEEN on the hot path, not just absent
    loop_quantized = loop_quantized_hidden = 0
    loop_exposed: List[Dict[str, Any]] = []
    for cname, instrs in comps.items():
        colls = [
            i for i in instrs if i.op in hlo_parse.COLLECTIVE_OPS and i.suffix != "-done"
        ]
        if not colls:
            continue
        defmap = {i.name: i for i in instrs}
        succ: Dict[str, List[str]] = {i.name: [] for i in instrs}
        pred: Dict[str, List[str]] = {i.name: [] for i in instrs}
        for i in instrs:
            for o in i.operands:
                if o in defmap:
                    succ[o].append(i.name)
                    pred[i.name].append(o)
        compute = [i for i in instrs if _is_real_compute(i, compute_comps)]
        in_loop = cname in loop_comps
        for c in colls:
            nbytes = hlo_parse.instruction_bytes(c)
            done = None
            if c.suffix == "-start":
                for j in instrs:
                    if j.op == c.op and j.suffix == "-done" and c.name in j.operands:
                        done = j
                        break
            if done is not None:
                async_pairs += 1
                desc = _reach([c.name], succ)
                hidden = any(
                    c.index < x.index < done.index and x.name not in desc
                    for x in compute
                )
            else:
                desc = _reach([c.name], succ)
                anc = _reach([c.name], pred)
                hidden = any(
                    x.name not in desc and x.name not in anc for x in compute
                )
            quantized = any(
                hlo_parse._QUANT_DTYPE_RE.match(dtype)
                for dtype, _ in hlo_parse._payload_shapes(
                    c.shape_str, c.suffix == "-start"
                )
            )
            if in_loop:
                loop_total += 1
                if quantized:
                    loop_quantized += 1
                    if hidden:
                        loop_quantized_hidden += 1
            if hidden:
                n_hidden += 1
                hidden_bytes += nbytes
            else:
                n_exposed += 1
                exposed_bytes += nbytes
                if in_loop:
                    loop_exposed.append(
                        {"computation": cname, "op": c.op, "name": c.name, "bytes": nbytes}
                    )

    verified = not loop_exposed
    res.summary = {
        "collectives": n_hidden + n_exposed,
        "hidden_count": n_hidden,
        "exposed_count": n_exposed,
        "hidden_bytes": hidden_bytes,
        "exposed_bytes": exposed_bytes,
        "async_pairs": async_pairs,
        "loop_collectives": loop_total,
        "loop_quantized": loop_quantized,
        "loop_quantized_hidden": loop_quantized_hidden,
        "loop_exposed": loop_exposed,
        "overlap_verified": verified,
    }
    severity = "error" if cfg.get("require_overlap") else "warn"
    for e in loop_exposed:
        res.violations.append(
            Violation(
                "overlap",
                art.name,
                f"{e['op']} ({e['bytes']} bytes/device) in loop body "
                f"{e['computation']} has no independent compute to hide "
                "behind: the collective is exposed on the critical path",
                severity=severity,
                details=e,
            )
        )

    # host-stream accounting mode (ZeRO-Infinity offload, ISSUE 16): the
    # engine declares its H2D/D2H stream schedule — per-bucket transfers,
    # each naming the compute program it hides behind — anchored to one
    # analyzed program. Transfers with no hiding program (pipeline knob
    # off), or naming a program NOT in the declared compute set (a schedule
    # cannot smuggle transfers behind phantom work), count as EXPOSED
    # stream bytes; the CI gate pins exposed_stream_bytes == 0.
    stream = cfg.get("offload_stream")
    if stream and art.name == stream.get("anchor"):
        known = set(stream.get("compute_programs", ()))
        transfers = list(stream.get("transfers", ()))
        s_h2d = s_d2h = s_exposed = 0
        stream_exposed: List[Dict[str, Any]] = []
        for t in transfers:
            b = int(t.get("bytes", 0))
            if t.get("direction") == "h2d":
                s_h2d += b
            else:
                s_d2h += b
            hide = t.get("hide_behind")
            if not hide or hide not in known:
                s_exposed += b
                stream_exposed.append(dict(t))
        res.summary.update(
            {
                "stream_transfers": len(transfers),
                "stream_h2d_bytes": s_h2d,
                "stream_d2h_bytes": s_d2h,
                "exposed_stream_bytes": s_exposed,
                "stream_exposed": stream_exposed,
                "stream_verified": s_exposed == 0,
            }
        )
        for t in stream_exposed:
            hide = t.get("hide_behind")
            why = (
                f"declares hiding program {hide!r} which is not in the "
                "declared compute set"
                if hide
                else "declares no hiding compute (pipeline knob off?)"
            )
            res.violations.append(
                Violation(
                    "overlap",
                    art.name,
                    f"offload {t.get('direction')} stream transfer "
                    f"{t.get('name')} ({t.get('bytes')} bytes) "
                    f"{why}: the stream is exposed on the step critical path",
                    severity=severity,
                    details=dict(t),
                )
            )
        budget = cfg.get("stream_budget_bytes")
        if budget is not None and budget >= 0 and (s_h2d + s_d2h) > budget:
            res.violations.append(
                Violation(
                    "overlap",
                    art.name,
                    f"declared offload stream traffic {s_h2d + s_d2h} bytes "
                    f"exceeds analysis.stream_budget_bytes={budget}",
                    severity="error",
                    details={"h2d_bytes": s_h2d, "d2h_bytes": s_d2h},
                )
            )
    return res


PROGRAM_PASSES: Dict[str, Callable[[ProgramArtifact, Optional[Dict[str, Any]]], PassResult]] = {
    "donation": donation_pass,
    "dtype_promotion": dtype_promotion_pass,
    "host_transfer": host_transfer_pass,
    "collectives": collectives_pass,
    "overlap": overlap_pass,
}


def analyze_program(
    name: str,
    wrapper,
    passes: Optional[Sequence[str]] = None,
    config: Optional[Dict[str, Any]] = None,
) -> Dict[str, PassResult]:
    """Run the selected passes (default: all) against one instrumented
    program. ``wrapper`` is a telemetry ``InstrumentedFunction`` (anything
    with ``trace_abstract()``)."""
    art = ProgramArtifact(name, wrapper)
    selected = list(passes) if passes else list(PROGRAM_PASSES)
    out: Dict[str, PassResult] = {}
    for pname in selected:
        if pname not in PROGRAM_PASSES:
            raise KeyError(
                f"unknown analysis pass {pname!r}; available: {sorted(PROGRAM_PASSES)}"
            )
        out[pname] = PROGRAM_PASSES[pname](art, config)
    return out
