"""Repo AST lint: the python-level hazards this codebase has been bitten by.

The program passes see what XLA compiled; this lint sees what python will do
*before* tracing ever happens — the class of bug that never reaches an HLO.
Rules (each one traces back to a real incident in PERF.md / PR history):

* **DS-R001 repeat-on-cache** — ``jnp.repeat`` applied to a cache-like
  array (k/v/cache/page/pool names): materializes a G-times copy of the
  widest buffer in the program (the PR-2 GQA decode blowup).
* **DS-R002 host-sync-in-jit** — ``.item()`` / ``float()`` / ``int()`` /
  ``bool()`` / ``np.asarray`` / ``jax.device_get`` applied to traced values
  inside a jitted function: a ConcretizationTypeError at best, a silent
  per-step host round-trip at worst.
* **DS-R003 shape-branch-in-jit** (warn) — python ``if`` on ``.shape`` /
  ``len()`` inside a jitted function: every new shape recompiles the
  program (fine when deliberate — annotate with a pragma).
* **DS-R004 jit-missing-donation** (warn) — a ``jax.jit`` / ``instrument``
  call without ``donate_argnums`` whose wrapped function takes a
  buffer-named parameter (grad_acc/opt_state/master/cache/pages/...):
  likely double-buffering a state-sized array.
* **DS-R005 host-transfer-in-serving-loop** — ``jax.device_get`` /
  ``.item()`` / ``np.asarray``-on-a-device-value inside the serving step
  loop (the step/round methods of a ``*Server`` / ``*Scheduler`` class,
  and the routing methods — apply/gate/dispatch/combine — of a ``*Gate``
  / ``*MoE`` / ``*MoELayer`` class, which run inside every traced step):
  every fetch beyond the one budgeted token fetch per dispatch adds a
  synchronous tunnel RTT (~2 ms, PERF.md) to EVERY serving round. The
  sanctioned single fetch per dispatch carries a pragma.
* **DS-R006 blocking-gather-in-scan-body** — a direct ``lax.all_gather`` /
  ``lax.psum`` on parameter-named values inside a function used as a
  ``lax.scan`` body: in the scanned layer stack those gathers belong to
  the comm-overlap pipeline (``zero.prefetch_layers``,
  ``runtime/zero/overlap.py``), which issues them a layer ahead of use —
  a hand-rolled blocking collective at the use point serializes the loop
  schedule the pipeline exists to overlap. Deliberate non-parameter or
  non-pipelined collectives carry a pragma.
* **DS-R008 non-atomic-persistence-write** — ``open(path, "w"/"wb")`` in a
  checkpoint / journal / bench-record code path (path or enclosing
  function named like one): a ``kill -9`` mid-write leaves a torn file
  that the ``latest`` marker, the known-good store, or a journal replay
  may then trust. Persist via write-to-temp → fsync → rename
  (``runtime/checkpoint_engine/atomic.py``); staged/temp writes (a
  tmp/staging/partial identifier in the path expression) are the
  sanctioned pattern and exempt. Append-mode opens are fine — append-only
  logs tolerate torn tails by design (CRC-gated replay).
* **DS-R009 raw-clock-in-step-loop** — a raw ``time.time()`` /
  ``time.perf_counter()`` / ``time.monotonic()`` call, or a ``device_sync``
  (full async-dispatch drain), inside a step-loop method of an
  ``*Engine`` / ``*Server`` / ``*Scheduler`` / ``*Loader`` class (the
  multi-step window family and the prefetching input pipeline run on the
  same critical path), or a routing method of a ``*Gate`` / ``*MoE`` /
  ``*MoELayer`` class (the expert dispatch path runs inside every traced
  step — a clock there stalls the a2a overlap): ad-hoc timing forks a
  second, invisible timeline next to the unified tracer (ISSUE 10), and a
  stray ``device_sync`` serializes host and device on every step (the
  ``SynchronizedWallClockTimer.stop(sync=True)`` default this PR removed).
  Route timing through the engine's tracer/timers (``profiling/tracer.py``,
  ``utils/timer.py`` — both files are out of scope for the rule, as is
  ``utils/sync.py``); deliberate exceptions carry a pragma. The host-offload
  ``*Streamer`` stream/writer family (ISSUE 16) is in scope twice over:
  its bucket methods are step-loop code (raw clocks flagged like any
  engine method), AND raw host copies (``device_put`` / ``device_get`` /
  ``copy_to_host_async`` / ``block_until_ready``) outside the sanctioned
  stream helpers (``h2d_bucket`` / ``d2h_bucket`` / ``_land`` /
  ``materialize_writes`` / ``drain_writes``) are flagged — an
  unaccounted copy never shows up in the stream-overlap analysis, so the
  "fully hidden behind compute" gate would silently lie.
* **DS-R010 jax-import-in-host-only-module** — an ``import jax`` /
  ``from jax ...`` (incl. ``jax.numpy``) anywhere in a module declared
  pure-host: the fleet router (``inference/fleet.py``) and the tracer
  (``profiling/tracer.py``). These components supervise/observe device
  work from OUTSIDE the device path — the router must keep routing,
  migrating, and journal-replaying while a replica's device backend is
  wedged, and the tracer's zero-transfer/zero-program guarantee rests on
  never touching jax. A jax dependency creeping in would silently couple
  them to backend init (the 25-minute tunnel stall class of failure).
* **DS-R007 pool-internals-mutated-outside-pool** — writing ``PagePool``
  internals (page tables, seq lens, free lists, refcounts, the prefix
  index, or the device cache) from outside the pool's own methods: the
  prefix-sharing pool holds CoW/refcount invariants (an indexed page is
  immutable; a shared page is never written; free ∪ cached ∪ referenced
  exactly partitions the pool) that only its methods preserve — a direct
  ``pool.page_table[...] = x`` or ``pool._free.append(p)`` corrupts KV
  silently. Go through ``alloc_slot`` / ``prepare_write`` / ``advance`` /
  ``rollback`` / ``free_slot`` / ``set_cache``; deliberate surgery (tests,
  checkpoint restore) carries a pragma.

* **DS-R011 unsharded-pool-placement** — a ``device_put`` of a pool/param-
  sized value (cache/pool/page/param/weight/master/kv/opt-state/buffer
  names) on a mesh code path whose placement argument is not a sharding:
  the PR-12 transient-OOM pattern — a full-size array committed to ONE
  chip before any reshard, transiently costing tp× the steady-state
  per-chip footprint on exactly the buffers sized against aggregate mesh
  HBM. Allocate directly sharded (``jax.jit(..., out_shardings=...)``) or
  place with a ``NamedSharding``; deliberate per-shard/host placements
  carry a pragma.
* **DS-R012 baked-constant-in-jit** (warn) — a module-level ndarray
  constant (``np.array(...)`` / ``jnp.zeros(...)`` / ...) closed over by a
  jitted function: the constant is baked into EVERY program that captures
  it (per-program HBM copies the ledger never sees) and a rebind
  silently retraces. Pass it as an argument (donated if large) or wrap
  the jit so the constant hashes into the cache key deliberately.

Suppression: append ``# lint: allow(DS-RXXX)`` (or ``# noqa: DS-RXXX``) to
the offending line. Findings in ``tests/`` are always downgraded to
warnings by the CLI — the gate is for the library.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

RULES = {
    "DS-R001": "jnp.repeat on a cache-like array (G-times buffer copy)",
    "DS-R002": "host sync on a traced value inside a jitted function",
    "DS-R003": "shape-dependent python branch inside a jitted function",
    "DS-R004": "jitted function with buffer-named args and no donate_argnums",
    "DS-R005": "host transfer inside the serving step loop (hot path)",
    "DS-R006": "blocking collective on parameters inside a scanned layer body",
    "DS-R007": "PagePool internals mutated outside the pool's own methods",
    "DS-R008": "non-atomic persistence write (open 'w' without temp+rename) in a checkpoint/journal/bench path",
    "DS-R009": "raw clock / device_sync / unsanctioned host copy inside an engine/scheduler/streamer step-loop method (route through the tracer/timer or the stream helpers)",
    "DS-R010": "jax import in a host-only module (the fleet router / tracer must stay pure host code)",
    "DS-R011": "device_put of a pool/param-sized value on a mesh path without a sharding (transient whole-buffer-on-one-chip OOM)",
    "DS-R012": "module-level ndarray constant closed over by a jitted function (baked per-program HBM copy + silent-retrace hazard)",
}
_WARN_ONLY = {"DS-R003", "DS-R004", "DS-R012"}

# DS-R010 scope: modules that must never import jax — the fleet router
# keeps serving decisions alive while device backends wedge, and the
# tracer's telemetry-is-free contract forbids any device coupling.
_R010_HOST_ONLY = re.compile(r"(inference/fleet\.py|profiling/tracer\.py)$")

# DS-R008 scope: files (or enclosing functions) that persist state other
# code will later trust — checkpoint layouts, journals, bench records.
_PERSIST_PATH = re.compile(r"(checkpoint|journal|bench|host_offload)", re.IGNORECASE)
_PERSIST_FN = re.compile(r"(checkpoint|journal|known_good|latest|marker)", re.IGNORECASE)
# the sanctioned atomic pattern: writes into a temp/staging sibling that a
# rename later commits
_TMPISH = re.compile(r"(tmp|temp|staging|partial|scratch)", re.IGNORECASE)

# DS-R007 scope: the pool state only pool methods may write. Distinctive
# names flag on ANY receiver; the generic ones (cache/_free/_owned/seq_lens
# collide with unrelated classes) only on a pool-ish receiver.
_POOL_ATTRS = {
    "page_table", "seq_lens", "cache", "_free", "_free_slots", "_owned",
    "_refcount", "_hash_index", "_page_hash", "_cached", "_chain_keys",
    "kv_sharding",
}
_POOL_DISTINCT = {
    "page_table", "_free_slots", "_refcount", "_hash_index", "_page_hash",
    "_chain_keys", "kv_sharding",
}
_POOLISH = re.compile(r"pool", re.IGNORECASE)
_POOL_CLASS = re.compile(r"Pool$")
_MUTATORS = {
    "append", "appendleft", "pop", "popleft", "popitem", "extend", "remove",
    "insert", "clear", "update", "setdefault", "sort", "reverse", "fill",
}

# DS-R006 operand scope: identifiers that look like model parameters — the
# values whose scan-body gathers the overlap pipeline owns. Activation /
# cotangent collectives (x, hidden, grads of activations) stay out of scope.
_PARAMISH = re.compile(
    r"(param|weight|^w$|^w\d+$|^w_|_w$|^wq$|^wk$|^wv$|^wo$|per_layer|layers?$)",
    re.IGNORECASE,
)
_SCAN_COLLECTIVES = {"all_gather", "psum"}

# DS-R005 scope: the per-round methods of a serving scheduler class — the
# code that runs between every device dispatch while requests stream. A
# class qualifies only when it BOTH matches the name pattern and defines a
# serving-specific round method, so host-only training-side schedulers
# (curriculum / random-LTD / compression `step()`s) stay out of scope.
# The ragged/window/TP family is in scope too (ISSUE 13): the sharded
# serving path runs the SAME one-fetch-per-dispatch budget, and a host
# transfer hidden in a tp/ragged step method costs every chip in the mesh.
_HOT_CLASS = re.compile(r"(Server|Scheduler)$")
_SERVING_FN = re.compile(
    r"^_?((plain_)?(decode|prefill|verify|spec|ragged|tp)_(step|round|window)|serve)$"
)
_HOT_FN = re.compile(
    r"^_?((plain_)?(decode|prefill|verify|spec|ragged|tp)_(step|round|window)"
    r"|settle_(ragged|window)_rows|settle_spec_row|step|run|serve)$"
)

# DS-R005/DS-R009 MoE routing scope (ISSUE 20): the gate/dispatch methods
# of a ``*Gate`` / ``*MoE`` / ``*MoELayer`` class run INSIDE every traced
# training and serving step — a host sync there (a ``.item()`` on an
# exp_counts, a clock around the dispatch) stalls the a2a overlap pipeline
# exactly like a fetch in a serving round. Unlike the Server/Scheduler
# scope there is no serving-method qualifier: a routing class IS hot by
# construction.
_MOE_CLASS = re.compile(r"(Gate|MoE|MoELayer)$")
_MOE_HOT_FN = re.compile(
    r"^_?(apply|forward|route|gate|gating|top\d?k?gating|dispatch|combine)$"
)
_NP_CASTS = ("np.asarray", "np.array", "numpy.asarray", "numpy.array", "onp.asarray")

# DS-R009 scope: step-loop methods of engine/server/scheduler classes —
# the code that runs between (or around) every hot dispatch — plus the
# input-pipeline Loader classes (ISSUE 14: a prefetching loader's __next__
# runs once per microbatch on the same critical path, and the multi-step
# window family — formation, per-step commit, deferred loss drain, lr
# pre-evaluation — runs between every window dispatch). The tracer /
# timer / sync modules OWN the clocks and are exempt by path.
_R009_EXEMPT_PATH = re.compile(r"(utils/timer\.py|utils/sync\.py|profiling/)")
_R009_CLASS = re.compile(r"(Engine|Server|Scheduler|Loader|Streamer)$")
_R009_FN = re.compile(
    r"^_?(forward|backward|step|train_batch|fused_train_batch|take_model_step"
    r"|take_offload_step|take_streamed_offload_step|generate"
    r"|(plain_)?(decode|prefill|verify|spec|ragged)"
    r"_(step|round)|admit|emit|run|serve|settle_spec_row|reserve_for_growth"
    r"|finish_step_bookkeeping|try_train_window|commit_window_step"
    r"|drain_pending|window_lrs|window_loader|__next__|pull|fill"
    r"|h2d_bucket|d2h_bucket|gather_device_state|scatter_device_state"
    r"|materialize_writes|drain_writes|discard_staged|take_staged|land)$"
)
# call names that read a raw clock or drain the dispatch queue
_R009_BASES = {"perf_counter", "monotonic", "device_sync", "perf_counter_ns", "monotonic_ns"}
_R009_EXACT = {"time.time", "time.clock", "_sync"}

# DS-R009 stream-copy discipline (ISSUE 16): inside a host-offload
# ``*Streamer`` class, every raw host copy must live in one of the
# sanctioned stream helpers — those are the only call sites the stream
# accounting (``stream_schedule`` → the overlap pass) knows about, and
# the only ones the step pipelines (double-buffered H2D, async D2H
# writer) order correctly against donation. ``__init__`` (seeding host
# buffers before any stepping) and ``set_master_leaves`` (checkpoint
# restore surgery) are sanctioned entry points too.
_STREAMER_CLASS = re.compile(r"Streamer$")
_STREAM_HELPER_FN = re.compile(r"^(__init__|_?set_master|_?(h2d|d2h|land|materialize|drain))")
_STREAM_COPY_BASES = {"device_put", "device_get", "copy_to_host_async", "block_until_ready"}

# DS-R011 scope: values sized like the buffers that OOM when transiently
# committed whole to one chip, and the argument spellings that count as a
# real sharding. "device" is deliberately NOT shard-ish — device_put(pool,
# jax.devices()[0]) is exactly the PR-12 incident. A placement-less
# device_put only flags on a mesh/shard/tp code path (enclosing-function
# identifiers) — default-device placement of host data is fine elsewhere.
_SIZEDISH = re.compile(
    r"(cache|pool|page|param|weight|master|^kv$|kv_|_kv$|opt_state|buffer)",
    re.IGNORECASE,
)
_SHARDISH = re.compile(r"(shard|spec|mesh|replicated)", re.IGNORECASE)
_MESHY = re.compile(r"(mesh|shard|tp_|_tp$|^tp$)", re.IGNORECASE)

# DS-R012 creators: module-level calls that build a host ndarray constant
_CONST_MAKERS = re.compile(
    r"^(np|numpy|jnp|onp|jax\.numpy)\.(array|asarray|ones|zeros|arange|full|"
    r"linspace|eye)$"
)

_CACHEY = re.compile(
    r"(cache|page|pool|buffer|^kv$|^k$|^v$|^k_|^v_|_kv$|kv_)", re.IGNORECASE
)
_BUFFER_PARAMS = {
    "grad_acc",
    "opt_state",
    "master",
    "cache",
    "pages",
    "k_pages",
    "v_pages",
    "kv_pages",
    "scale_state",
}
_SHAPEISH = {"shape", "ndim", "size", "dtype"}
_PRAGMA = re.compile(r"(#\s*lint:\s*allow\(([^)]*)\)|#\s*noqa:\s*([\w,\s-]+))")


@dataclass
class LintFinding:
    path: str
    line: int
    rule: str
    message: str
    severity: str = "error"  # resolved by the caller per path

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}/{self.severity}] {self.message}"


def _dotted(node: ast.AST) -> str:
    """'jnp.repeat' for Attribute chains, 'float' for Names, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _identifiers(node: ast.AST) -> Set[str]:
    names = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            names.add(n.id)
        elif isinstance(n, ast.Attribute):
            names.add(n.attr)
    return names


def _is_shapeish(node: ast.AST) -> bool:
    """True when the expression only reads static structure (shapes, dims,
    literals) — a trace-time constant, not a traced value."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _SHAPEISH:
            return True
        if isinstance(n, ast.Call) and _dotted(n.func) == "len":
            return True
    return False


class _JitCollector(ast.NodeVisitor):
    """First walk: which function names / lambda nodes get jitted here."""

    JIT_FUNCS = {"jit", "jax.jit", "pjit", "_jit"}

    def __init__(self):
        self.jitted_names: Set[str] = set()
        self.jitted_lambdas: List[ast.Lambda] = []
        self.jit_calls: List[ast.Call] = []  # for DS-R004

    def _is_jit_call(self, call: ast.Call) -> bool:
        name = _dotted(call.func)
        return (
            name in self.JIT_FUNCS
            or name.endswith(".jit")
            or name.endswith(".instrument")
            or name == "instrument"
        )

    def visit_Call(self, node: ast.Call) -> None:
        if self._is_jit_call(node):
            self.jit_calls.append(node)
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    self.jitted_names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    self.jitted_lambdas.append(arg)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = _dotted(target)
            if name in self.JIT_FUNCS or name.endswith(".jit"):
                self.jitted_names.add(node.name)
            if isinstance(dec, ast.Call) and name.endswith("partial"):
                for a in dec.args:
                    if _dotted(a).endswith("jit"):
                        self.jitted_names.add(node.name)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _fn_params(fn) -> Set[str]:
    args = fn.args
    names = [a.arg for a in args.args + args.posonlyargs + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def lint_source(src: str, path: str = "<string>") -> List[LintFinding]:
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [LintFinding(path, e.lineno or 0, "DS-R000", f"syntax error: {e.msg}")]
    lines = src.splitlines()
    findings: List[LintFinding] = []

    def allowed(lineno: int, rule: str) -> bool:
        if 1 <= lineno <= len(lines):
            m = _PRAGMA.search(lines[lineno - 1])
            if m:
                codes = (m.group(2) or m.group(3) or "")
                return rule in codes or codes.strip() == "*"
        return False

    def add(lineno: int, rule: str, message: str) -> None:
        if not allowed(lineno, rule):
            findings.append(LintFinding(path, lineno, rule, message))

    collector = _JitCollector()
    collector.visit(tree)

    # resolve jitted names to FunctionDef nodes (module-wide, nearest wins
    # is irrelevant — scrutinize every def carrying a jitted name)
    fn_defs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_defs.setdefault(node.name, []).append(node)

    jit_bodies: List[ast.AST] = list(collector.jitted_lambdas)
    for name in collector.jitted_names:
        jit_bodies.extend(fn_defs.get(name, []))

    # ---- DS-R001: anywhere in the file --------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if not (fname.endswith(".repeat") and not fname.startswith("re.")):
            continue
        # the repeated array is args[0] in the function form
        # (jnp.repeat(k_cache, G)) and the RECEIVER in the method form
        # (k_cache.repeat(G)) — scan both
        idents = set()
        if node.args:
            idents |= _identifiers(node.args[0])
        if isinstance(node.func, ast.Attribute):
            idents |= _identifiers(node.func.value)
        if any(_CACHEY.search(i) for i in idents):
            add(
                node.lineno,
                "DS-R001",
                f"repeat on cache-like array ({', '.join(sorted(idents)[:3])}): "
                "use grouped einsum instead of expanding kv heads",
            )

    # ---- DS-R002/R003 inside jitted bodies ----------------------------
    seen_nodes: Set[int] = set()
    for body in jit_bodies:
        if id(body) in seen_nodes:
            continue
        seen_nodes.add(id(body))
        params = _fn_params(body)
        # closures: parameters of nested defs also count as traced values
        for n in ast.walk(body):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                params |= _fn_params(n)
        for n in ast.walk(body):
            if isinstance(n, ast.Call):
                fname = _dotted(n.func)
                if (
                    (fname == "item" or fname.endswith(".item"))
                    and isinstance(n.func, ast.Attribute)
                    and not n.args
                ):
                    add(n.lineno, "DS-R002", ".item() on a traced value inside jit")
                elif fname in ("jax.device_get", "device_get"):
                    add(n.lineno, "DS-R002", "jax.device_get inside a jitted function")
                elif fname in ("np.asarray", "np.array", "numpy.asarray", "numpy.array", "onp.asarray"):
                    if n.args and isinstance(n.args[0], ast.Name) and n.args[0].id in params:
                        add(
                            n.lineno,
                            "DS-R002",
                            f"{fname} on traced argument {n.args[0].id!r} inside jit",
                        )
                elif fname in ("float", "int", "bool") and n.args:
                    arg = n.args[0]
                    if (
                        not _is_shapeish(arg)
                        and not isinstance(arg, ast.Constant)
                        and (_identifiers(arg) & params)
                    ):
                        add(
                            n.lineno,
                            "DS-R002",
                            f"{fname}() on a traced value inside jit "
                            "(concretizes or silently syncs)",
                        )
            elif isinstance(n, ast.If):
                if _is_shapeish(n.test) and (_identifiers(n.test) & params):
                    add(
                        n.lineno,
                        "DS-R003",
                        "shape-dependent python branch inside a jitted function "
                        "(each new shape recompiles)",
                    )

    # ---- DS-R005: host transfers in the serving hot loop --------------
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        if _HOT_CLASS.search(cls.name):
            if not any(
                isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and _SERVING_FN.match(m.name)
                for m in cls.body
            ):
                continue  # a host-only scheduler, not the serving loop
            fn_re, kind = _HOT_FN, "serving hot path"
        elif _MOE_CLASS.search(cls.name):
            fn_re, kind = _MOE_HOT_FN, "MoE routing path"
        else:
            continue
        for fn in cls.body:
            if not (
                isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn_re.match(fn.name)
            ):
                continue
            where = f"{kind} {cls.name}.{fn.name}"
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                fname = _dotted(n.func)
                if fname in ("jax.device_get", "device_get"):
                    add(n.lineno, "DS-R005", f"jax.device_get in {where}")
                elif (
                    (fname == "item" or fname.endswith(".item"))
                    and isinstance(n.func, ast.Attribute)
                    and not n.args
                ):
                    add(n.lineno, "DS-R005", f".item() in {where}")
                elif fname in _NP_CASTS and n.args and isinstance(
                    # literals (lists/tuples/constants) build host arrays;
                    # names/attributes/calls/subscripts can hide a device
                    # value whose np conversion is a blocking transfer
                    n.args[0], (ast.Name, ast.Attribute, ast.Call, ast.Subscript)
                ):
                    add(
                        n.lineno,
                        "DS-R005",
                        f"{fname} on a possible device value in {where} "
                        "(one fetch per dispatch is the budget)",
                    )

    # ---- DS-R009: raw clocks / device syncs in step-loop methods ------
    if not _R009_EXEMPT_PATH.search(path.replace(os.sep, "/")):
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if _R009_CLASS.search(cls.name):
                fn_re = _R009_FN
            elif _MOE_CLASS.search(cls.name):
                fn_re = _MOE_HOT_FN  # gate/dispatch methods: same step path
            else:
                continue
            for fn in cls.body:
                if not (
                    isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn_re.match(fn.name)
                ):
                    continue
                where = f"step-loop method {cls.name}.{fn.name}"
                for n in ast.walk(fn):
                    if not isinstance(n, ast.Call):
                        continue
                    fname = _dotted(n.func)
                    base = fname.rsplit(".", 1)[-1]
                    if fname in _R009_EXACT or base in _R009_BASES:
                        add(
                            n.lineno,
                            "DS-R009",
                            f"raw {fname}() in {where}: ad-hoc clocks fork the "
                            "timeline (and device_sync serializes the step) — "
                            "route through the engine tracer/timer",
                        )

        # stream-copy discipline: raw host copies in a *Streamer class
        # outside the sanctioned stream helpers bypass the stream
        # accounting the overlap gate audits
        for cls in ast.walk(tree):
            if not (isinstance(cls, ast.ClassDef) and _STREAMER_CLASS.search(cls.name)):
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if _STREAM_HELPER_FN.match(fn.name):
                    continue  # the sanctioned copy helpers own the raw calls
                for n in ast.walk(fn):
                    if not isinstance(n, ast.Call):
                        continue
                    base = _dotted(n.func).rsplit(".", 1)[-1]
                    if base in _STREAM_COPY_BASES:
                        add(
                            n.lineno,
                            "DS-R009",
                            f"raw {base} in {cls.name}.{fn.name}: host copies "
                            "outside the sanctioned stream helpers (h2d_bucket/"
                            "d2h_bucket/materialize_writes/drain_writes) never "
                            "enter the stream accounting, so the overlap gate "
                            "can't see them",
                        )

    # ---- DS-R006: blocking param collectives in scan bodies -----------
    scan_bodies: List[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if not (fname == "scan" or fname.endswith(".scan")):
            continue
        if node.args:
            body_arg = node.args[0]
            if isinstance(body_arg, ast.Name):
                scan_bodies.extend(fn_defs.get(body_arg.id, []))
            elif isinstance(body_arg, ast.Lambda):
                scan_bodies.append(body_arg)
    seen_scan: Set[int] = set()
    for body in scan_bodies:
        if id(body) in seen_scan:
            continue
        seen_scan.add(id(body))
        for n in ast.walk(body):
            if not isinstance(n, ast.Call):
                continue
            fname = _dotted(n.func)
            base = fname.rsplit(".", 1)[-1]
            if base not in _SCAN_COLLECTIVES:
                continue
            operand_idents = _identifiers(n.args[0]) if n.args else set()
            if any(_PARAMISH.search(i) for i in operand_idents):
                add(
                    n.lineno,
                    "DS-R006",
                    f"blocking {base} on parameter-like value "
                    f"({', '.join(sorted(operand_idents)[:3])}) inside a "
                    "lax.scan body: the comm-overlap pipeline "
                    "(zero.prefetch_layers) should own this gather",
                )

    # ---- DS-R007: pool internals mutated outside the pool -------------
    def _pool_attr(node):
        """(attr, receiver) when ``node`` is ``<recv>.<protected attr>``
        (possibly through a subscript), else None."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) and node.attr in _POOL_ATTRS:
            return node.attr, _dotted(node.value)
        return None

    def _flag_r007(node, attr, recv, how):
        if attr in _POOL_DISTINCT or _POOLISH.search(recv or ""):
            add(
                node.lineno,
                "DS-R007",
                f"{how} of PagePool internal {recv or '<expr>'}.{attr} outside "
                "the pool's methods breaks the CoW/refcount invariants (use "
                "alloc_slot/prepare_write/advance/rollback/free_slot/set_cache)",
            )

    def _scan_r007(node, in_pool):
        if isinstance(node, ast.ClassDef) and _POOL_CLASS.search(node.name):
            in_pool = True  # the pool's own methods are the sanctioned writers
        if not in_pool:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = (
                    [node.target] if isinstance(node, ast.AugAssign)
                    else node.targets
                )
                flat = []
                for t in targets:
                    flat.extend(
                        t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                    )
                for t in flat:
                    hit = _pool_attr(t)
                    if hit:
                        _flag_r007(node, hit[0], hit[1], "write")
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _MUTATORS:
                    hit = _pool_attr(node.func.value)
                    if hit:
                        _flag_r007(node, hit[0], hit[1], f".{node.func.attr}()")
        for child in ast.iter_child_nodes(node):
            _scan_r007(child, in_pool)

    _scan_r007(tree, False)

    # ---- DS-R008: non-atomic persistence writes -----------------------
    file_in_scope = bool(_PERSIST_PATH.search(path.replace(os.sep, "/")))

    def _write_mode(call: ast.Call) -> Optional[str]:
        mode = None
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if isinstance(mode, str) and "w" in mode:
            return mode
        return None

    def _tmpish_path(arg: ast.AST) -> bool:
        for n in ast.walk(arg):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                if _TMPISH.search(n.value):
                    return True
        return any(_TMPISH.search(i) for i in _identifiers(arg))

    def _scan_r008(node, fn_in_scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_in_scope = fn_in_scope or bool(_PERSIST_FN.search(node.name))
        if (
            isinstance(node, ast.Call)
            and _dotted(node.func) == "open"
            and (file_in_scope or fn_in_scope)
            and node.args
        ):
            mode = _write_mode(node)
            if mode is not None and not _tmpish_path(node.args[0]):
                add(
                    node.lineno,
                    "DS-R008",
                    f"open(..., {mode!r}) in a persistence path: a kill "
                    "mid-write leaves a torn file later readers trust — "
                    "write to a temp sibling and rename "
                    "(runtime/checkpoint_engine/atomic.py)",
                )
        for child in ast.iter_child_nodes(node):
            _scan_r008(child, fn_in_scope)

    _scan_r008(tree, False)

    # ---- DS-R010: jax imports in host-only modules --------------------
    if _R010_HOST_ONLY.search(path.replace(os.sep, "/")):
        for node in ast.walk(tree):
            bad = None
            if isinstance(node, ast.Import):
                bad = next(
                    (a.name for a in node.names
                     if a.name == "jax" or a.name.startswith("jax.")),
                    None,
                )
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.module == "jax" or node.module.startswith("jax."):
                    bad = node.module
            if bad:
                add(
                    node.lineno,
                    "DS-R010",
                    f"import of {bad!r} in host-only module {os.path.basename(path)}: "
                    "the fleet router / tracer must keep working while the "
                    "device backend is wedged — keep them pure host code",
                )

    # ---- DS-R011: unsharded pool-sized placements ---------------------
    def _scan_r011(node, fn_idents: Optional[Set[str]]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # the enclosing function's identifier soup (its name, parameter
            # names, and every identifier in the body) decides whether a
            # placement-less device_put sits on a mesh path
            fn_idents = _identifiers(node) | _fn_params(node) | {node.name}
        if isinstance(node, ast.Call) and _dotted(node.func).rsplit(".", 1)[
            -1
        ] == "device_put":
            arg_idents = _identifiers(node.args[0]) if node.args else set()
            sized = sorted(i for i in arg_idents if _SIZEDISH.search(i))
            placement = node.args[1] if len(node.args) >= 2 else None
            if placement is None:
                for kw in node.keywords:
                    if kw.arg in ("device", "sharding", "shardings"):
                        placement = kw.value
            if sized:
                if placement is None:
                    if fn_idents is not None and any(
                        _MESHY.search(i) for i in fn_idents
                    ):
                        add(
                            node.lineno,
                            "DS-R011",
                            f"device_put of pool/param-sized value "
                            f"({', '.join(sized[:3])}) with no sharding on a "
                            "mesh path: the whole buffer transiently commits "
                            "to one chip (tp x the per-chip footprint) — "
                            "allocate directly sharded "
                            "(jit(..., out_shardings=...)) or pass a "
                            "NamedSharding",
                        )
                elif not any(_SHARDISH.search(i) for i in _identifiers(placement)):
                    add(
                        node.lineno,
                        "DS-R011",
                        f"device_put of pool/param-sized value "
                        f"({', '.join(sized[:3])}) onto a non-sharding "
                        "placement: the whole buffer lands on one chip before "
                        "any reshard (the PR-12 transient OOM) — place with a "
                        "NamedSharding or allocate via out_shardings",
                    )
        for child in ast.iter_child_nodes(node):
            _scan_r011(child, fn_idents)

    _scan_r011(tree, None)

    # ---- DS-R012: module-level ndarray constants captured by jit ------
    const_lines: Dict[str, int] = {}
    for stmt in tree.body:  # module level only: the bake-forever captures
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if _CONST_MAKERS.match(_dotted(stmt.value.func)):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        const_lines[t.id] = stmt.lineno
    if const_lines:
        seen_r012: Set[int] = set()
        for body in jit_bodies:
            if id(body) in seen_r012:
                continue
            seen_r012.add(id(body))
            local: Set[str] = set(_fn_params(body))
            for n in ast.walk(body):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    local |= _fn_params(n)
                elif isinstance(n, (ast.Assign, ast.AugAssign)):
                    targets = (
                        [n.target] if isinstance(n, ast.AugAssign) else n.targets
                    )
                    for t in targets:
                        if isinstance(t, ast.Name):
                            local.add(t.id)
            flagged: Set[str] = set()
            for n in ast.walk(body):
                if (
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in const_lines
                    and n.id not in local
                    and n.id not in flagged
                ):
                    flagged.add(n.id)
                    add(
                        n.lineno,
                        "DS-R012",
                        f"jitted function closes over module-level ndarray "
                        f"constant {n.id!r} (defined line "
                        f"{const_lines[n.id]}): the array is baked into every "
                        "capturing program (untracked per-program HBM) and a "
                        "rebind silently retraces — pass it as an argument",
                    )

    # ---- DS-R004: jit call sites without donation ---------------------
    for call in collector.jit_calls:
        kwnames = {kw.arg for kw in call.keywords if kw.arg}
        if "donate_argnums" in kwnames or "donate_argnames" in kwnames:
            continue
        for arg in call.args:
            fn = None
            if isinstance(arg, ast.Name):
                defs = fn_defs.get(arg.id)
                fn = defs[-1] if defs else None
            elif isinstance(arg, ast.Lambda):
                fn = arg
            if fn is None:
                continue
            hit = _fn_params(fn) & _BUFFER_PARAMS
            if hit:
                add(
                    call.lineno,
                    "DS-R004",
                    f"jitted function takes buffer args ({', '.join(sorted(hit))}) "
                    "but the jit call declares no donate_argnums",
                )
                break
    return findings


def lint_paths(paths: Sequence[str]) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for root in paths:
        if os.path.isfile(root):
            files = [root]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(
                    os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
                )
        for f in sorted(files):
            try:
                with open(f, "r", encoding="utf-8") as fh:
                    src = fh.read()
            except (OSError, UnicodeDecodeError):
                continue
            findings.extend(lint_source(src, f))
    return findings


def resolve_severity(finding: LintFinding, warn_prefixes: Sequence[str] = ("tests",)) -> str:
    """tests/ (and any other warn prefix) never fails the gate; warn-only
    rules never fail anywhere."""
    if finding.rule in _WARN_ONLY:
        return "warn"
    norm = finding.path.replace(os.sep, "/")
    for p in warn_prefixes:
        if norm.startswith(p.rstrip("/") + "/") or f"/{p.rstrip('/')}/" in norm:
            return "warn"
    return "error"


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json as _json

    ap = argparse.ArgumentParser(description="deepspeed_tpu repo AST lint")
    ap.add_argument("paths", nargs="*", default=["deepspeed_tpu", "tests"])
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--json",
        action="store_true",
        help="shorthand for --format json (structured output for CI gates)",
    )
    ap.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="DS-RXXX",
        help="only report findings of these rule id(s); repeatable",
    )
    ap.add_argument(
        "--warn-prefix",
        action="append",
        default=None,
        help="path prefixes whose findings are warn-only (default: tests)",
    )
    ns = ap.parse_args(argv)
    if ns.json:
        ns.format = "json"
    warn_prefixes = ns.warn_prefix if ns.warn_prefix else ["tests"]
    findings = lint_paths(ns.paths)
    if ns.rule:
        wanted = set(ns.rule)
        unknown = wanted - set(RULES) - {"DS-R000"}
        if unknown:
            ap.error(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        findings = [f for f in findings if f.rule in wanted]
    n_err = 0
    for f in findings:
        f.severity = resolve_severity(f, warn_prefixes)
        if f.severity == "error":
            n_err += 1
    if ns.format == "json":
        print(_json.dumps([f.__dict__ for f in findings], indent=1))
    else:
        for f in findings:
            print(f.render())
        print(f"lint: {len(findings)} finding(s), {n_err} error(s)")
    return 1 if n_err else 0
