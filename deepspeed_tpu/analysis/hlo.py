"""Post-optimization HLO text parsing.

The analysis passes that need *compile-time truth* — which donated buffers
XLA actually aliased, which collectives GSPMD actually inserted, whether a
host round-trip survived into the executable — read it from
``compiled.as_text()``. Lowered StableHLO is not enough: SPMD partitioning
inserts the collectives and the alias table is only fixed at compile time.

Everything here is plain-text parsing of the stable parts of HLO syntax
(``HloModule`` header attributes, ``%name = shape op-name(...)`` op lines);
each helper degrades to "no results" rather than raising when the dialect
drifts, so analysis stays best-effort on new XLA releases.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Set

# HLO primitive-type byte widths (packed 4-bit types round up per element)
_DTYPE_BYTES = {
    "pred": 1,
    "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
# f8e4m3fn / f8e5m2 / f8e4m3b11fnuz ... — all one byte
_F8_RE = re.compile(r"^f8e\w+$")

_SHAPE_RE = re.compile(r"\b(pred|[suf]\d+|bf16|c64|c128|f8e\w+)\[([\d,]*)\]")

# collective op names as they appear in optimized HLO; async pairs are
# counted once on the -start half
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)
_COLLECTIVE_RE = re.compile(
    r"=\s*((?:\([^)]*\))|(?:[\w\[\]{},]+))\s+("
    + "|".join(re.escape(op) for op in COLLECTIVE_OPS)
    + r")(-start|-done)?\("
)

# host-boundary ops: infeed/outfeed/send/recv plus python-callback
# custom-calls (pure_callback / io_callback / debug lowerings)
_HOST_OP_RE = re.compile(r"=\s*(?:\([^)]*\)|[\w\[\]{},]+)\s+(infeed|outfeed|send|recv)\(")
_CALLBACK_TARGET_RE = re.compile(
    r'custom_call_target="([^"]*(?:callback|python|host)[^"]*)"', re.IGNORECASE
)
_METADATA_OP_RE = re.compile(r'op_name="([^"]*)"')


def dtype_bytes(dtype: str) -> int:
    if dtype in _DTYPE_BYTES:
        return _DTYPE_BYTES[dtype]
    if _F8_RE.match(dtype):
        return 1
    return 4  # unknown type: assume word-sized rather than dropping the op


def _shapes_bytes(shapes) -> int:
    """Total bytes of ``(dtype, dims)`` pairs as matched by ``_SHAPE_RE`` —
    the ONE copy of the byte-accounting math every extractor shares."""
    total = 0
    for dtype, dims in shapes:
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * dtype_bytes(dtype)
    return total


def shape_list_bytes(shape_str: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape inside ``shape_str``
    (handles tuple shapes: ``(f32[2,4]{1,0}, f32[])``). Shapes in optimized
    SPMD HLO are per-partition, so the result is bytes *per participating
    device*."""
    return _shapes_bytes(_SHAPE_RE.findall(shape_str))


def async_start_result_bytes(shape_str: str) -> int:
    """Bytes of the RESULT half of an async ``-start`` bundle shape
    (``(operands..., results...)``) — the convention that keeps sync and
    async lowerings of one collective reporting identical totals (operands
    would otherwise double-count). Trailing ``u32[]``/``s32[]`` scalars are
    scheduler context, not payload (collective-permute-start's
    ``(src, dest, u32[], u32[])`` form) — counting them as the "result
    half" would report ~8 bytes for an N-element permute. Falls back to
    every payload shape when the bundle doesn't split evenly."""
    shapes = _SHAPE_RE.findall(shape_str)
    while shapes and shapes[-1][0] in ("u32", "s32") and not shapes[-1][1]:
        shapes = shapes[:-1]
    if len(shapes) >= 2 and len(shapes) % 2 == 0:
        shapes = shapes[len(shapes) // 2 :]
    return _shapes_bytes(shapes)


def module_header(hlo_text: str) -> str:
    for line in hlo_text.splitlines():
        if line.startswith("HloModule"):
            return line
    return ""


def parse_input_output_aliases(hlo_text: str) -> Set[int]:
    """Parameter indices the compiled module aliases to an output — the
    donations XLA honored. Parsed from the header's
    ``input_output_alias={ {out}: (param, {path}, kind), ... }`` table."""
    header = module_header(hlo_text)
    m = re.search(r"input_output_alias=\{(.*?)\},\s*\w+=", header)
    if m is None:
        # table may be last attribute on the line
        m = re.search(r"input_output_alias=\{(.*)\}", header)
    if m is None:
        return set()
    return {int(p) for p in re.findall(r":\s*\(\s*(\d+)", m.group(1))}


_PARAM_LINE_RE = re.compile(
    r"=\s*((?:\((?:[^()]|\([^()]*\))*\))|[\w\[\]{},]+)\s+parameter\((\d+)\)"
)
_ENTRY_RESULT_RE = re.compile(r"->\s*(.*?)\s*\{\s*$")


def entry_parameter_shapes(hlo_text: str) -> Dict[int, str]:
    """{parameter index: shape string} of the ENTRY computation — the
    per-chip input buffers of the compiled executable (shapes in optimized
    SPMD HLO are per-partition). Best-effort: unparseable lines drop out."""
    out: Dict[int, str] = {}
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if not in_entry:
            continue
        m = _PARAM_LINE_RE.search(line)
        if m:
            out[int(m.group(2))] = m.group(1)
        if line.strip() == "}":
            break
    return out


def entry_result_shape(hlo_text: str) -> Optional[str]:
    """Shape string of the ENTRY computation's result (the ``-> shape {``
    of its header; falls back to the ROOT instruction line), or None."""
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            m = _ENTRY_RESULT_RE.search(line)
            if m:
                return m.group(1)
            continue
        if not in_entry:
            continue
        s = line.strip()
        if s.startswith("ROOT "):
            m = re.search(r"=\s*((?:\((?:[^()]|\([^()]*\))*\))|[\w\[\]{},]+)\s+", s)
            if m:
                return m.group(1)
        if s == "}":
            break
    return None


def entry_parameter_count(hlo_text: str) -> Optional[int]:
    """Number of entry-computation parameters, or None if unparseable.
    Used to detect argument pruning (``len(flat args_info)`` mismatch)."""
    lines = hlo_text.splitlines()
    start = None
    for i, line in enumerate(lines):
        if line.startswith("ENTRY "):
            start = i
            break
    if start is None:
        return None
    idxs = []
    for line in lines[start:]:
        idxs.extend(int(i) for i in re.findall(r"=\s*[\w\[\]{},()]+\s+parameter\((\d+)\)", line))
        if line.strip() == "}":
            break
    return (max(idxs) + 1) if idxs else 0


def collect_collectives(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """Static collective schedule: per op kind, occurrence count and total
    payload bytes (per participating device, summed over occurrences).
    Async ``-start``/``-done`` pairs count once, on the start half —
    counting only the RESULT half of the start's ``(operands..., results...)``
    bundle shape, so sync and async lowerings of the same program report
    identical byte totals (async starts would otherwise double-count every
    operand)."""
    out: Dict[str, Dict[str, Any]] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shape_str, op, suffix = m.group(1), m.group(2), m.group(3)
        if suffix == "-done":
            continue
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        if suffix == "-start":
            rec["bytes"] += async_start_result_bytes(shape_str)
        else:
            rec["bytes"] += shape_list_bytes(shape_str)
    return out


# quantized wire dtypes: the EQuARX-style exchanges move int8 (or packed
# sub-byte / f8) payloads — 1 byte on the wire where fp32 moves 4
_QUANT_DTYPE_RE = re.compile(r"^([su](2|4|8)|f8e\w+)$")
# replica group forms: explicit {{0,1,2,3},{4,5,6,7}}, iota [2,4]<=[8],
# and the empty form {} (= one group of ALL participating devices)
_GROUPS_FIRST_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_GROUPS_EMPTY_RE = re.compile(r"replica_groups=\{\s*\}")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")


def module_num_partitions(hlo_text: str) -> Optional[int]:
    """``num_partitions`` from the module header — the world size the
    empty ``replica_groups={}`` form implies."""
    m = _NUM_PARTITIONS_RE.search(module_header(hlo_text))
    return int(m.group(1)) if m else None


def replica_group_size(attrs: str, world: Optional[int] = None) -> Optional[int]:
    """Participants per replica group of a collective op line.
    ``replica_groups={}`` (XLA's spelling for one group of every
    participating device) resolves to ``world`` (the module's
    num_partitions) when given. None when absent/unparseable —
    best-effort contract."""
    m = _GROUPS_FIRST_RE.search(attrs)
    if m:
        return len(m.group(1).split(","))
    m = _GROUPS_IOTA_RE.search(attrs)
    if m:
        return int(m.group(2))
    if _GROUPS_EMPTY_RE.search(attrs):
        return world
    return None


def wire_factor(op: str, group: Optional[int]) -> float:
    """Per-device wire bytes of a collective as a multiple of its payload
    bytes, under the standard ring/bidirectional cost model: an all-reduce
    moves its payload twice (reduce-scatter + all-gather phases, each
    ``(g-1)/g``); gather/scatter/exchange ops move it once. The factor is
    what turns the static payload schedule into the comm cost model PERF.md
    budgets (and what makes "int8 exchange = fp all-reduce / 4" an exact
    accounting identity: 2·(g-1)/g·4N fp bytes vs 2·(g-1)/g·N int8 bytes)."""
    if group is None or group <= 1:
        return 0.0 if group == 1 else 1.0
    frac = (group - 1) / group
    if op == "all-reduce":
        return 2.0 * frac
    if op in ("all-gather", "reduce-scatter", "all-to-all", "collective-broadcast"):
        return frac
    return 1.0  # collective-permute and anything unrecognized: one hop


def _payload_shapes(shape_str: str, is_start: bool):
    """(dtype, dims) payload pairs of one collective's shape string, with
    the async ``-start`` operand half trimmed per
    ``async_start_result_bytes``'s convention."""
    shapes = _SHAPE_RE.findall(shape_str)
    if is_start:
        while shapes and shapes[-1][0] in ("u32", "s32") and not shapes[-1][1]:
            shapes = shapes[:-1]
        if len(shapes) >= 2 and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2 :]
    return shapes


def collect_collective_details(hlo_text: str) -> List[Dict[str, Any]]:
    """Per-occurrence collective records with dtype-aware byte accounting:
    ``{op, bytes, wire_bytes, quantized_bytes, quantized_wire_bytes,
    fp_equiv_wire_bytes, group}``. ``bytes`` matches
    ``collect_collectives``'s payload accounting; ``wire_bytes`` applies
    the per-device ring cost model (``wire_factor``); the ``quantized_*``
    fields isolate sub-byte/int8/f8 payloads (the EQuARX exchanges) and
    ``fp_equiv_wire_bytes`` prices the same element count at fp32 — the
    comparison the quantized-comms acceptance gate asserts."""
    out: List[Dict[str, Any]] = []
    world = module_num_partitions(hlo_text)
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        shape_str, op, suffix = m.group(1), m.group(2), m.group(3)
        if suffix == "-done":
            continue
        shapes = _payload_shapes(shape_str, suffix == "-start")
        group = replica_group_size(line, world=world)
        wf = wire_factor(op, group)
        rec = {
            "op": op,
            "group": group,
            "bytes": 0,
            "wire_bytes": 0.0,
            "quantized_bytes": 0,
            "quantized_wire_bytes": 0.0,
            "fp_equiv_wire_bytes": 0.0,
        }
        for dtype, dims in shapes:
            n = 1
            if dims:
                for d in dims.split(","):
                    n *= int(d)
            b = n * dtype_bytes(dtype)
            rec["bytes"] += b
            rec["wire_bytes"] += b * wf
            if _QUANT_DTYPE_RE.match(dtype):
                rec["quantized_bytes"] += b
                rec["quantized_wire_bytes"] += b * wf
                rec["fp_equiv_wire_bytes"] += n * 4 * wf
        out.append(rec)
    return out


class HloInstruction:
    """One parsed op line of an HLO computation."""

    __slots__ = ("name", "op", "suffix", "shape_str", "operands", "attrs", "index")

    def __init__(self, name, op, suffix, shape_str, operands, attrs, index):
        self.name = name
        self.op = op  # base op name ("all-gather", "fusion", "dot", ...)
        self.suffix = suffix  # "-start" | "-done" | ""
        self.shape_str = shape_str
        self.operands = operands  # %-referenced names (over-approximate)
        self.attrs = attrs  # raw text after the operand list
        self.index = index  # position in the computation (the schedule
        # order: optimized modules carry is_scheduled=true)


_COMP_HEADER_RE = re.compile(r"^(ENTRY\s+)?%([\w.$-]+)\s*\(.*\)\s*->.*\{\s*$")
# the shape group must swallow tuple shapes nested two levels deep:
# variadic async combiner starts (TPU AllGatherCombiner et al.) have
# ``((operands...), (results...))`` bundle shapes — a flat ``\([^)]*\)``
# stops at the first inner ')' and silently drops the instruction, which
# would let an exposed loop collective go unseen by the overlap pass
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.$-]+)\s*=\s*"
    r"((?:\((?:[^()]|\([^()]*\))*\))|[\w\[\]{},]+)\s+([\w-]+)\("
)
_REF_RE = re.compile(r"%([\w.$-]+)")
_ASYNC_SUFFIX_RE = re.compile(r"^(.*?)(-start|-done)$")


def parse_computations(hlo_text: str):
    """{computation name: [HloInstruction]} for every computation in the
    module, plus the entry computation's name. Operand lists are the
    %-referenced names on the op line — an over-approximation (attribute
    refs like ``calls=%fused_computation.2`` point at computations, which
    never collide with same-computation instruction names, so they drop out
    of the dependency maps)."""
    comps: Dict[str, List[HloInstruction]] = {}
    entry: Optional[str] = None
    cur: Optional[str] = None
    for line in hlo_text.splitlines():
        h = _COMP_HEADER_RE.match(line)
        if h:
            cur = h.group(2)
            comps[cur] = []
            if h.group(1):
                entry = cur
            continue
        if line.strip() == "}":
            cur = None
            continue
        if cur is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, shape_str, opname = m.group(1), m.group(2), m.group(3)
        suffix = ""
        am = _ASYNC_SUFFIX_RE.match(opname)
        if am and am.group(1) in COLLECTIVE_OPS:
            opname, suffix = am.group(1), am.group(2)
        rest = line[m.end() :]
        operands = [r for r in _REF_RE.findall(rest) if r != name]
        comps[cur].append(
            HloInstruction(
                name, opname, suffix, shape_str, operands, rest, len(comps[cur])
            )
        )
    return comps, entry


def while_body_computations(hlo_text: str) -> Set[str]:
    """Names of computations executed as while-loop bodies (the lowered form
    of ``lax.scan`` — where the training layer pipeline lives)."""
    return set(re.findall(r"body=%([\w.$-]+)", hlo_text))


def instruction_bytes(instr: "HloInstruction") -> int:
    """Result payload bytes of one instruction. Async ``-start`` bundle
    shapes carry ``(operands..., results...)`` — count the result half so
    sync and async lowerings report identical totals (collect_collectives'
    convention)."""
    if instr.suffix == "-start":
        return async_start_result_bytes(instr.shape_str)
    return shape_list_bytes(instr.shape_str)


def find_host_ops(hlo_text: str) -> List[Dict[str, str]]:
    """Host-boundary ops that survived into the executable: infeed/outfeed/
    send/recv and python-callback custom-calls, each with the jax op_name
    from its metadata when present."""
    found: List[Dict[str, str]] = []
    for line in hlo_text.splitlines():
        m = _HOST_OP_RE.search(line)
        kind = None
        if m:
            kind = m.group(1)
        else:
            cb = _CALLBACK_TARGET_RE.search(line)
            if cb and "custom-call" in line:
                kind = f"custom-call:{cb.group(1)}"
        if kind is None:
            continue
        meta = _METADATA_OP_RE.search(line)
        found.append({"op": kind, "jax_op": meta.group(1) if meta else ""})
    return found
