"""Post-optimization HLO text parsing.

The analysis passes that need *compile-time truth* — which donated buffers
XLA actually aliased, which collectives GSPMD actually inserted, whether a
host round-trip survived into the executable — read it from
``compiled.as_text()``. Lowered StableHLO is not enough: SPMD partitioning
inserts the collectives and the alias table is only fixed at compile time.

Everything here is plain-text parsing of the stable parts of HLO syntax
(``HloModule`` header attributes, ``%name = shape op-name(...)`` op lines);
each helper degrades to "no results" rather than raising when the dialect
drifts, so analysis stays best-effort on new XLA releases.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Set

# HLO primitive-type byte widths (packed 4-bit types round up per element)
_DTYPE_BYTES = {
    "pred": 1,
    "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
# f8e4m3fn / f8e5m2 / f8e4m3b11fnuz ... — all one byte
_F8_RE = re.compile(r"^f8e\w+$")

_SHAPE_RE = re.compile(r"\b(pred|[suf]\d+|bf16|c64|c128|f8e\w+)\[([\d,]*)\]")

# collective op names as they appear in optimized HLO; async pairs are
# counted once on the -start half
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)
_COLLECTIVE_RE = re.compile(
    r"=\s*((?:\([^)]*\))|(?:[\w\[\]{},]+))\s+("
    + "|".join(re.escape(op) for op in COLLECTIVE_OPS)
    + r")(-start|-done)?\("
)

# host-boundary ops: infeed/outfeed/send/recv plus python-callback
# custom-calls (pure_callback / io_callback / debug lowerings)
_HOST_OP_RE = re.compile(r"=\s*(?:\([^)]*\)|[\w\[\]{},]+)\s+(infeed|outfeed|send|recv)\(")
_CALLBACK_TARGET_RE = re.compile(
    r'custom_call_target="([^"]*(?:callback|python|host)[^"]*)"', re.IGNORECASE
)
_METADATA_OP_RE = re.compile(r'op_name="([^"]*)"')


def dtype_bytes(dtype: str) -> int:
    if dtype in _DTYPE_BYTES:
        return _DTYPE_BYTES[dtype]
    if _F8_RE.match(dtype):
        return 1
    return 4  # unknown type: assume word-sized rather than dropping the op


def shape_list_bytes(shape_str: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape inside ``shape_str``
    (handles tuple shapes: ``(f32[2,4]{1,0}, f32[])``). Shapes in optimized
    SPMD HLO are per-partition, so the result is bytes *per participating
    device*."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * dtype_bytes(dtype)
    return total


def module_header(hlo_text: str) -> str:
    for line in hlo_text.splitlines():
        if line.startswith("HloModule"):
            return line
    return ""


def parse_input_output_aliases(hlo_text: str) -> Set[int]:
    """Parameter indices the compiled module aliases to an output — the
    donations XLA honored. Parsed from the header's
    ``input_output_alias={ {out}: (param, {path}, kind), ... }`` table."""
    header = module_header(hlo_text)
    m = re.search(r"input_output_alias=\{(.*?)\},\s*\w+=", header)
    if m is None:
        # table may be last attribute on the line
        m = re.search(r"input_output_alias=\{(.*)\}", header)
    if m is None:
        return set()
    return {int(p) for p in re.findall(r":\s*\(\s*(\d+)", m.group(1))}


def entry_parameter_count(hlo_text: str) -> Optional[int]:
    """Number of entry-computation parameters, or None if unparseable.
    Used to detect argument pruning (``len(flat args_info)`` mismatch)."""
    lines = hlo_text.splitlines()
    start = None
    for i, line in enumerate(lines):
        if line.startswith("ENTRY "):
            start = i
            break
    if start is None:
        return None
    idxs = []
    for line in lines[start:]:
        idxs.extend(int(i) for i in re.findall(r"=\s*[\w\[\]{},()]+\s+parameter\((\d+)\)", line))
        if line.strip() == "}":
            break
    return (max(idxs) + 1) if idxs else 0


def collect_collectives(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """Static collective schedule: per op kind, occurrence count and total
    payload bytes (per participating device, summed over occurrences).
    Async ``-start``/``-done`` pairs count once, on the start half —
    counting only the RESULT half of the start's ``(operands..., results...)``
    bundle shape, so sync and async lowerings of the same program report
    identical byte totals (async starts would otherwise double-count every
    operand)."""
    out: Dict[str, Dict[str, Any]] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shape_str, op, suffix = m.group(1), m.group(2), m.group(3)
        if suffix == "-done":
            continue
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        if suffix == "-start":
            shapes = _SHAPE_RE.findall(shape_str)
            if len(shapes) >= 2 and len(shapes) % 2 == 0:
                shapes = shapes[len(shapes) // 2 :]  # results only
            nbytes = 0
            for dtype, dims in shapes:
                n = 1
                if dims:
                    for d in dims.split(","):
                        n *= int(d)
                nbytes += n * dtype_bytes(dtype)
            rec["bytes"] += nbytes
        else:
            rec["bytes"] += shape_list_bytes(shape_str)
    return out


def find_host_ops(hlo_text: str) -> List[Dict[str, str]]:
    """Host-boundary ops that survived into the executable: infeed/outfeed/
    send/recv and python-callback custom-calls, each with the jax op_name
    from its metadata when present."""
    found: List[Dict[str, str]] = []
    for line in hlo_text.splitlines():
        m = _HOST_OP_RE.search(line)
        kind = None
        if m:
            kind = m.group(1)
        else:
            cb = _CALLBACK_TARGET_RE.search(line)
            if cb and "custom-call" in line:
                kind = f"custom-call:{cb.group(1)}"
        if kind is None:
            continue
        meta = _METADATA_OP_RE.search(line)
        found.append({"op": kind, "jax_op": meta.group(1) if meta else ""})
    return found
