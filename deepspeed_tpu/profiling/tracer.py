"""Unified tracing & metrics plane (ISSUE 10).

Five telemetry surfaces grew up siloed — ``compile_stats()``,
``analysis_report()``, ``serve_stats()``, ``checkpoint_stats()``, and the
bench fields — and none of them can answer "where did step N's 11 ms go?"
or "what was the server doing in the 200 ms before it died?". This module
is the shared timeline + metrics substrate underneath all of them:

* :class:`Tracer` — span-based structured tracing. Spans are **host-side
  only** (monotonic ``time.perf_counter`` stamps around host phases; device
  time is inferred from the dispatch-enqueue and blocking-fetch boundaries
  the engines already have), nest via a per-thread stack, and land in a
  bounded ring buffer (``collections.deque(maxlen=...)``) so a long-running
  server holds the LAST window of activity, not an unbounded log. Appends
  are lock-guarded and the per-thread nesting state is ``threading.local``,
  so the async checkpoint writer and the serving loop can trace
  concurrently. The hard hot-path contract (enforced by the
  telemetry-is-free tests): tracing performs **zero host↔device transfers
  and compiles zero new programs** — nothing in this file imports jax.
* :class:`MetricsRegistry` — named counters / gauges / fixed-bucket
  histograms (p50/p99 via bucket interpolation), thread-safe, cheap enough
  for per-step observation.
* Chrome-trace export — :meth:`Tracer.export_chrome_trace` writes the
  Trace Event Format JSON that Perfetto / ``chrome://tracing`` load
  directly: complete (``X``) events for spans, instant (``i``) events,
  async (``b``/``n``/``e``) events for request lifecycles.
* :class:`FlightRecorder` — the crash postmortem: dump the ring buffer +
  open spans + metrics snapshot to a JSON file on ``atexit``, on a signal,
  or on a ``utils/chaos.py`` fault injection (the chaos kill hook fires
  before ``os._exit``/``ChaosKilled``, so every fault-injection kill from
  the PR-8 matrix leaves a parseable postmortem naming the armed point).
* :class:`ObservabilityHub` — the one-call merge: ``engine.observability()``
  returns the timeline + metrics next to the engine's existing stat
  surfaces (compile / analysis / serve / checkpoint), and
  :meth:`ObservabilityHub.monitor_events` turns the current metrics into
  the ``(name, value, step)`` events the ``monitor/`` backends fan out.
  The fleet router (``inference/fleet.py`` — the other module bound by
  this file's never-import-jax contract, lint DS-R010) traces its own
  span family on the same timeline (``fleet.step`` > ``fleet.replica_step``
  per replica, plus ``fleet.route`` / ``fleet.migrate`` / ``fleet.drain``
  and ``fleet.replica_dead`` / ``fleet.join`` instants) and registers a
  ``fleet`` source via ``FleetRouter.attach_observability(hub)``, so one
  report shows the router's supervision next to each replica's serving
  phases.

Overhead discipline: a disabled tracer's ``span()`` returns a shared no-op
context manager (one attribute read + one call); an enabled span costs two
clock reads, one small dict, and one lock-guarded deque append — single-digit
microseconds against multi-millisecond steps. The guard test pins the
measured overhead under 2% of a bench-like step.
"""

from __future__ import annotations

import atexit
import bisect
import json
import os
import signal as _signal
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Tracer",
    "NULL_TRACER",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "FlightRecorder",
    "ObservabilityHub",
]


def _atomic_json_dump(path: str, payload) -> str:
    """Temp + fsync + rename JSON write: a concurrent reader (or a crash
    mid-dump) never sees a torn file. Local on purpose — this module must
    not import ``runtime/checkpoint_engine/atomic.py`` (the tracer's
    no-jax-import constraint is load-bearing)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class _NullSpan:
    """Shared no-op span: what a disabled tracer hands out. Duration reads
    0 so callers deriving timings from it must check ``tracer.enabled``."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> "_NullSpan":  # noqa: ARG002
        return self

    @property
    def duration_ms(self) -> float:
        return 0.0


_NULL_SPAN = _NullSpan()


class _Span:
    """A live span: context manager that stamps perf_counter on entry/exit,
    tracks nesting depth through the tracer's per-thread stack, and appends
    one completed record to the ring buffer on exit."""

    __slots__ = ("_tr", "name", "attrs", "t0", "t1", "depth")

    def __init__(self, tracer: "Tracer", name: str, attrs: Optional[Dict]):
        self._tr = tracer
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self.t1 = 0.0
        self.depth = 0

    def __enter__(self) -> "_Span":
        tr = self._tr
        stack = tr._stack()
        self.depth = len(stack)
        stack.append(self)  # the stack IS the open-span registry (no lock)
        self.t0 = tr.clock()
        return self

    def __exit__(self, *exc) -> bool:
        tr = self._tr
        self.t1 = tr.clock()
        stack = tr._stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # unbalanced exit (exception unwound past us)
            stack.remove(self)
        tr._append(
            {
                "ph": "X",
                "name": self.name,
                "t0": self.t0,
                "t1": self.t1,
                "tid": threading.get_ident(),
                "depth": self.depth,
                "attrs": self.attrs,
            }
        )
        return False

    def set(self, **attrs) -> "_Span":
        """Attach/overwrite attributes mid-span (e.g. a row count known only
        after packing)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    @property
    def duration_ms(self) -> float:
        return (self.t1 - self.t0) * 1e3


class Tracer:
    """Span/event recorder over a bounded ring buffer.

    ``enabled=False`` makes every recording call a near-free no-op (the
    shared :data:`_NULL_SPAN` / an early return); flipping ``enabled`` at
    runtime is safe (the bench uses it to measure tracing overhead).
    ``clock`` is injectable for tests; it must be monotonic.
    """

    def __init__(
        self,
        max_spans: int = 4096,
        enabled: bool = True,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if max_spans < 1:
            raise ValueError(f"max_spans must be >= 1, got {max_spans}")
        self.enabled = bool(enabled)
        self.clock = clock
        self.max_spans = int(max_spans)
        self._buf: deque = deque(maxlen=self.max_spans)
        self._total = 0
        self._lock = threading.Lock()
        self._local = threading.local()
        # tid -> that thread's open-span stack: the per-thread nesting state
        # doubles as the open-span registry (open_spans() walks these), so
        # span enter/exit pays ZERO lock acquisitions — only the completed-
        # record append takes the lock
        self._stacks: Dict[int, List[_Span]] = {}
        # wall-clock anchor so exported traces carry absolute timestamps
        self._anchor = (time.time(), self.clock())

    # --- internals ------------------------------------------------------
    def _stack(self) -> List[_Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
            with self._lock:
                self._stacks[threading.get_ident()] = st
        return st

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(rec)
            self._total += 1

    # --- recording surface ----------------------------------------------
    def span(self, name: str, **attrs):
        """Context manager timing one host-side phase. Nest freely; the
        record carries the nesting depth and thread id."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs or None)

    def add_span(self, name: str, t0: float, t1: float, **attrs) -> None:
        """Record a span from explicit clock() stamps (the timer module and
        the comm wrappers route through this — they own their own timing)."""
        if not self.enabled:
            return
        self._append(
            {
                "ph": "X",
                "name": name,
                "t0": t0,
                "t1": t1,
                "tid": threading.get_ident(),
                "depth": len(self._stack()),
                "attrs": attrs or None,
            }
        )

    def event(self, name: str, **attrs) -> None:
        """Instant event (a point in time, not a duration)."""
        if not self.enabled:
            return
        now = self.clock()
        self._append(
            {
                "ph": "i",
                "name": name,
                "t0": now,
                "t1": now,
                "tid": threading.get_ident(),
                "depth": len(self._stack()),
                "attrs": attrs or None,
            }
        )

    # async (long-running, cross-step) spans — request lifecycles
    def begin_async(self, cat: str, aid: Any, name: str, **attrs) -> None:
        self._async(cat, aid, name, "b", attrs)

    def instant_async(self, cat: str, aid: Any, name: str, **attrs) -> None:
        self._async(cat, aid, name, "n", attrs)

    def end_async(self, cat: str, aid: Any, name: str, **attrs) -> None:
        self._async(cat, aid, name, "e", attrs)

    def _async(self, cat: str, aid: Any, name: str, ph: str, attrs: Dict) -> None:
        if not self.enabled:
            return
        now = self.clock()
        self._append(
            {
                "ph": ph,
                "cat": cat,
                "id": aid,
                "name": name,
                "t0": now,
                "t1": now,
                "tid": threading.get_ident(),
                "depth": 0,
                "attrs": attrs or None,
            }
        )

    # --- read surface ----------------------------------------------------
    def spans(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """Snapshot of the ring buffer (oldest first); ``last`` trims to the
        newest N records."""
        with self._lock:
            out = list(self._buf)
        return out[-last:] if last else out

    def open_spans(self) -> List[Dict[str, Any]]:
        """Spans currently in flight on ANY thread — the flight recorder's
        'what was it doing when it died' answer. Best-effort snapshot of
        the per-thread stacks (a span entering/exiting concurrently may be
        missed or doubled; fine for a postmortem)."""
        with self._lock:
            stacks = list(self._stacks.values())
        now = self.clock()
        return [
            {
                "name": s.name,
                "t0": s.t0,
                "elapsed_ms": (now - s.t0) * 1e3,
                "depth": s.depth,
                "attrs": s.attrs,
            }
            for st in stacks
            for s in list(st)
        ]

    def dropped(self) -> int:
        with self._lock:
            return max(0, self._total - len(self._buf))

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._total = 0

    def phase_summary(self) -> Dict[str, Dict[str, float]]:
        """Aggregate completed spans by name: count, total/mean/max ms.
        The bench's ``step_phase_ms`` breakdown and the monitor feed read
        this."""
        out: Dict[str, Dict[str, float]] = {}
        for rec in self.spans():
            if rec["ph"] != "X":
                continue
            ms = (rec["t1"] - rec["t0"]) * 1e3
            agg = out.get(rec["name"])
            if agg is None:
                out[rec["name"]] = {"count": 1, "total_ms": ms, "max_ms": ms}
            else:
                agg["count"] += 1
                agg["total_ms"] += ms
                if ms > agg["max_ms"]:
                    agg["max_ms"] = ms
        for agg in out.values():
            agg["mean_ms"] = agg["total_ms"] / agg["count"]
            agg["total_ms"] = round(agg["total_ms"], 4)
            agg["mean_ms"] = round(agg["mean_ms"], 4)
            agg["max_ms"] = round(agg["max_ms"], 4)
        return out

    def summary(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "spans": len(self._buf),
            "dropped": self.dropped(),
            "open": [s["name"] for s in self.open_spans()],
            "phases": self.phase_summary(),
        }

    # --- Chrome-trace (Perfetto) export ----------------------------------
    def export_chrome_trace(
        self, path: str, metrics: Optional["MetricsRegistry"] = None
    ) -> str:
        """Write the ring buffer as Trace Event Format JSON (the format
        ``chrome://tracing`` and https://ui.perfetto.dev load directly).
        Span times become microsecond offsets from the tracer's anchor;
        the wall-clock anchor and an optional metrics snapshot ride in
        ``otherData``. Returns the written path. The write is
        temp+rename-atomic so a concurrently-read file is never torn."""
        wall0, perf0 = self._anchor
        events: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": os.getpid(),
                "args": {"name": "deepspeed_tpu"},
            }
        ]
        for rec in self.spans():
            ts = round((rec["t0"] - perf0) * 1e6, 3)
            ev: Dict[str, Any] = {
                "name": rec["name"],
                "ph": rec["ph"],
                "pid": os.getpid(),
                "tid": rec["tid"],
                "ts": ts,
            }
            if rec["ph"] == "X":
                ev["dur"] = round((rec["t1"] - rec["t0"]) * 1e6, 3)
            elif rec["ph"] == "i":
                ev["s"] = "t"  # thread-scoped instant
            elif rec["ph"] in ("b", "n", "e"):
                ev["cat"] = rec.get("cat", "async")
                ev["id"] = str(rec.get("id"))
            if rec.get("attrs"):
                ev["args"] = rec["attrs"]
            events.append(ev)
        payload: Dict[str, Any] = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "anchor_unix_time": wall0,
                "dropped_spans": self.dropped(),
            },
        }
        if metrics is not None:
            payload["otherData"]["metrics"] = metrics.snapshot()
        return _atomic_json_dump(path, payload)


NULL_TRACER = Tracer(max_spans=1, enabled=False)
"""Shared disabled tracer: a safe default argument so instrumented code
never branches on ``tracer is None``."""


def percentile_summary(values) -> Dict[str, float]:
    """``{count, mean, p50, p99}`` summary of a host-side sample
    (``{'count': 0}`` when empty) — linear interpolation, matching
    numpy's default percentile method. Lives here (stdlib-only, never
    imports jax or numpy) so BOTH the scheduler's per-tenant latency
    stats and the fleet router's merged stats share one definition —
    the router is a DS-R010 host-only module that cannot import the
    scheduler."""
    vals = sorted(float(v) for v in values)
    n = len(vals)
    if n == 0:
        return {"count": 0}

    def pct(q: float) -> float:
        if n == 1:
            return vals[0]
        pos = q / 100.0 * (n - 1)
        lo = int(pos)
        hi = min(lo + 1, n - 1)
        return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)

    return {
        "count": n,
        "mean": sum(vals) / n,
        "p50": pct(50.0),
        "p99": pct(99.0),
    }


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v

    def snapshot(self) -> float:
        return self._v


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        return self._v

    def snapshot(self) -> float:
        return self._v


# generic latency-ish bounds (unit-agnostic; default reads naturally as ms)
_DEFAULT_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    Observations land in ``len(bounds)+1`` buckets (the last is the
    overflow). ``percentile`` walks the cumulative counts and linearly
    interpolates inside the landing bucket — exact min/max observed values
    clamp the ends, so p50/p99 are always within the observed range."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_min", "_max", "_lock")

    def __init__(self, name: str, buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets or _DEFAULT_BUCKETS))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        idx = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, p: float) -> float:
        """Interpolated percentile in [0, 100]; 0.0 when empty."""
        with self._lock:
            total = self._count
            if total == 0:
                return 0.0
            counts = list(self._counts)
            lo_obs, hi_obs = self._min, self._max
        target = max(1.0, p / 100.0 * total)
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.bounds[i - 1] if i > 0 else lo_obs
                hi = self.bounds[i] if i < len(self.bounds) else hi_obs
                frac = (target - cum) / c
                val = lo + (hi - lo) * frac
                return min(max(val, lo_obs), hi_obs)
            cum += c
        return hi_obs

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            out = {
                "count": self._count,
                "sum": round(self._sum, 6),
                "mean": round(self._sum / self._count, 6),
                "min": self._min,
                "max": self._max,
            }
        out["p50"] = round(self.percentile(50), 6)
        out["p99"] = round(self.percentile(99), 6)
        return out


class MetricsRegistry:
    """Named metric store: get-or-create counters/gauges/histograms.
    Re-requesting a name returns the SAME instance; requesting it as a
    different kind raises (a silent shadow would split the series)."""

    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, kind, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = kind(name, *args)
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {kind.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, buckets: Optional[Sequence[float]] = None) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Histogram(name, buckets)
            elif not isinstance(m, Histogram):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested Histogram"
                )
            return m

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(items):
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Crash postmortem: the last K spans + open spans + metrics, dumped to
    a JSON file when the process dies.

    Three triggers, all opt-in via :meth:`install`:

    * ``atexit`` — a clean interpreter exit leaves a final dump (reason
      ``"exit"``).
    * signals — SIGTERM/SIGINT etc.: dump, then chain to the previous
      handler (so the preemption SIGTERM of a TPU slice still terminates).
    * the ``utils/chaos.py`` kill hook — fires BEFORE the chaos action
      (``ChaosKilled`` raise or the real ``os._exit(137)``), records a
      ``chaos.<point>`` event as the timeline's last entry, and dumps with
      the armed point named. Every fault-injection kill from the PR-8
      matrix therefore leaves a postmortem whose last span names the
      injection point.

    Dumps are temp+rename-atomic; repeated dumps overwrite (latest wins).
    """

    def __init__(
        self,
        tracer: Tracer,
        metrics: Optional[MetricsRegistry] = None,
        path: Optional[str] = None,
        dump_dir: Optional[str] = None,
        last_spans: int = 256,
    ):
        if path is None:
            dump_dir = dump_dir or "."
            path = os.path.join(dump_dir, f"flight_recorder_{os.getpid()}.json")
        self.tracer = tracer
        self.metrics = metrics
        self.path = path
        self.last_spans = int(last_spans)
        self._installed: List[Callable[[], None]] = []
        self._prev_handlers: Dict[int, Any] = {}
        # free-form armed-config block carried into every dump payload —
        # e.g. the serving layer records the multi-step window horizon so
        # a postmortem showing serve.window spans names its configuration
        self.context: Dict[str, Any] = {}
        self.dumps = 0

    # --- triggers --------------------------------------------------------
    def install(
        self,
        on_exit: bool = True,
        signals: Sequence[int] = (),
        chaos: bool = True,
    ) -> "FlightRecorder":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if on_exit:
            atexit.register(self._atexit_dump)
            self._installed.append(lambda: atexit.unregister(self._atexit_dump))
        for sig in signals:
            prev = _signal.signal(sig, self._signal_dump)
            self._prev_handlers[sig] = prev
        if chaos:
            from deepspeed_tpu.utils import chaos as chaos_mod

            chaos_mod.add_kill_hook(self._chaos_dump)
            self._installed.append(
                lambda: chaos_mod.remove_kill_hook(self._chaos_dump)
            )
        return self

    def uninstall(self) -> None:
        for undo in self._installed:
            try:
                undo()
            except Exception:
                pass
        self._installed.clear()
        for sig, prev in self._prev_handlers.items():
            try:
                _signal.signal(sig, prev)
            except Exception:
                pass
        self._prev_handlers.clear()

    def _atexit_dump(self) -> None:
        try:
            self.dump(reason="exit")
        except Exception:
            pass  # a failing postmortem must never mask the real exit

    def _signal_dump(self, signum, frame) -> None:
        try:
            self.tracer.event(f"signal.{signum}")
            self.dump(reason="signal", point=str(signum))
        except Exception:
            pass
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev != _signal.SIG_IGN:
            # SIG_DFL, or None (installed by non-Python code — unknowable,
            # so fail toward termination): restore the default disposition
            # and re-raise, never swallow a kill signal
            _signal.signal(signum, _signal.SIG_DFL)
            _signal.raise_signal(signum)

    def _chaos_dump(self, point: str, action: str) -> None:
        # the chaos event becomes the timeline's LAST entry: a postmortem
        # reader (and the test matrix) can match it to the armed point
        self.tracer.event(f"chaos.{point}", action=action)
        self.dump(reason="chaos", point=point)

    # --- the dump --------------------------------------------------------
    def dump(self, reason: str = "manual", point: Optional[str] = None) -> str:
        from deepspeed_tpu.utils import chaos as chaos_mod

        sched = chaos_mod.active()
        payload = {
            "reason": reason,
            "point": point,
            "pid": os.getpid(),
            "wall_time": time.time(),
            "context": dict(self.context),
            "dropped_spans": self.tracer.dropped(),
            "open_spans": self.tracer.open_spans(),
            "spans": self.tracer.spans(last=self.last_spans),
            "metrics": self.metrics.snapshot() if self.metrics else None,
            "chaos_fired": list(sched.fired_log) if sched is not None else [],
        }
        _atomic_json_dump(self.path, payload)
        self.dumps += 1
        return self.path


# ---------------------------------------------------------------------------
# the hub
# ---------------------------------------------------------------------------
class ObservabilityHub:
    """One merged observability surface per engine.

    Holds the engine's tracer + metrics and a dict of named stat sources
    (callables returning dicts — ``compile_stats``, ``analysis_report``,
    ``serve_stats``, ``checkpoint_stats``). :meth:`report` is what
    ``engine.observability()`` returns: the live timeline and metrics next
    to every registered surface, each guarded so one failing source never
    hides the others."""

    def __init__(self, tracer: Tracer, metrics: MetricsRegistry):
        self.tracer = tracer
        self.metrics = metrics
        self._sources: Dict[str, Callable[[], Any]] = {}
        self.flight_recorder: Optional[FlightRecorder] = None

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        self._sources[name] = fn

    def report(self, exclude: Sequence[str] = ()) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "timeline": self.tracer.summary(),
            "metrics": self.metrics.snapshot(),
        }
        for name, fn in self._sources.items():
            if name in exclude:
                continue
            try:
                out[name] = fn()
            except Exception as e:  # surface, never mask the siblings
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def export_chrome_trace(self, path: str) -> str:
        return self.tracer.export_chrome_trace(path, metrics=self.metrics)

    def install_flight_recorder(
        self,
        path: Optional[str] = None,
        dump_dir: Optional[str] = None,
        last_spans: int = 256,
        on_exit: bool = True,
        signals: Sequence[int] = (),
        chaos: bool = True,
    ) -> FlightRecorder:
        if self.flight_recorder is not None:
            self.flight_recorder.uninstall()
        self.flight_recorder = FlightRecorder(
            self.tracer, self.metrics, path=path, dump_dir=dump_dir,
            last_spans=last_spans,
        ).install(on_exit=on_exit, signals=signals, chaos=chaos)
        return self.flight_recorder

    def monitor_events(self, step: int) -> List[Tuple[str, float, int]]:
        """The periodic monitor feed: phase means from the timeline plus
        every registered metric, as ``(name, value, step)`` events for
        ``MonitorMaster.write_events``."""
        events: List[Tuple[str, float, int]] = []
        for name, agg in sorted(self.tracer.phase_summary().items()):
            events.append((f"Trace/{name}/mean_ms", float(agg["mean_ms"]), step))
        snap = self.metrics.snapshot()
        for name, v in snap["counters"].items():
            events.append((f"Metrics/{name}", float(v), step))
        for name, v in snap["gauges"].items():
            events.append((f"Metrics/{name}", float(v), step))
        for name, h in snap["histograms"].items():
            if h.get("count"):
                events.append((f"Metrics/{name}/p50", float(h["p50"]), step))
                events.append((f"Metrics/{name}/p99", float(h["p99"]), step))
        return events
