"""FLOPs profiler.

Counterpart of the reference's ``FlopsProfiler``
(``deepspeed/profiling/flops_profiler/profiler.py:28``). The reference
monkey-patches ~40 torch functionals and hooks every module to count MACs;
under XLA the compiler already knows — ``Compiled.cost_analysis()`` returns
the exact flops/bytes of the optimized program. The profiler therefore:

* pulls flops / bytes-accessed / peak-memory from the compiled train step
  (``get_compiled_cost``),
* measures wall latency around the profiled step,
* derives the reference's headline numbers (``get_total_flops``,
  ``get_total_params``, flops/s, MFU) and prints the same style of summary
  (``print_model_profile``).

``get_model_profile`` (reference :1039) profiles a standalone model callable
the same way.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _num_to_string(num: float, precision: int = 2) -> str:
    if num >= 1e12:
        return f"{num / 1e12:.{precision}f} T"
    if num >= 1e9:
        return f"{num / 1e9:.{precision}f} G"
    if num >= 1e6:
        return f"{num / 1e6:.{precision}f} M"
    if num >= 1e3:
        return f"{num / 1e3:.{precision}f} K"
    return f"{num:.{precision}f} "


def number_to_string(num, units=None, precision=2):
    return _num_to_string(num, precision)


def flops_to_string(flops, units=None, precision=2):
    return _num_to_string(flops, precision) + "FLOPS"


def params_to_string(params_num, units=None, precision=2):
    return _num_to_string(params_num, precision).strip()


def macs_to_string(macs, units=None, precision=2):
    return _num_to_string(macs, precision) + "MACs"


def duration_to_string(duration, units=None, precision=2):
    if duration > 1:
        return f"{duration:.{precision}f} s"
    if duration > 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


def get_compiled_cost(jitted_fn, *args, **kwargs) -> Dict[str, float]:
    """flops / bytes / peak memory of the compiled program via XLA's own
    cost model (the ground truth the reference approximates hook-by-hook)."""
    lowered = jitted_fn.lower(*args, **kwargs)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    cost = dict(cost or {})
    out = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0))),
        "transcendentals": float(cost.get("transcendentals", 0.0)),
    }
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["peak_memory_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
            )
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# per-module profile tree
# ---------------------------------------------------------------------------
class ModuleProfile:
    """One node of the per-module tree (reference profiler.py:85-130 prints
    this per hooked nn.Module; here nodes come from the model's streamable
    decomposition — embed / layer_i / head — each compiled and cost-analyzed
    as its own XLA program)."""

    def __init__(self, name: str, depth: int, params: int, flops: float, latency: float):
        self.name = name
        self.depth = depth
        self.params = params
        self.flops = flops
        self.macs = flops / 2
        self.latency = latency
        self.children: list = []

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "depth": self.depth,
            "params": self.params,
            "macs": self.macs,
            "flops": self.flops,
            "latency": self.latency,
            "children": [c.as_dict() for c in self.children],
        }


def _tree_params(tree) -> int:
    import jax

    return sum(int(np.prod(np.shape(l))) for l in jax.tree_util.tree_leaves(tree))


def _time_jitted(fn, *args, runs: int = 3) -> float:
    import jax

    out = fn(*args)  # compile + warm
    jax.tree_util.tree_map(lambda x: getattr(x, "block_until_ready", lambda: x)(), out)
    t0 = time.perf_counter()
    for _ in range(runs):
        out = fn(*args)
    jax.tree_util.tree_map(lambda x: getattr(x, "block_until_ready", lambda: x)(), out)
    return (time.perf_counter() - t0) / runs


def get_module_profile(module, params, tokens, runs: int = 3) -> ModuleProfile:
    """Per-module profile tree of a layer-streamable model.

    The model's ``stream_fns`` decomposition (embed → layer × L → head,
    ``models/transformer.py:467``) already names the module boundaries the
    reference walks with hooks; each part is jitted separately so XLA's
    ``cost_analysis`` gives its exact flops and a timed run gives real
    per-module latency. Layers share one compiled program, so the per-layer
    flops/latency are measured once and attributed to every layer row
    (layer params are counted per layer from the stacked tree).
    """
    import jax
    import jax.numpy as jnp

    if not hasattr(module, "stream_fns"):
        raise ValueError(
            "per-module profiling needs a layer-streamable model exposing "
            f"stream_fns(); got {type(module).__name__}"
        )
    embed_fwd, layer_fwd, head_loss = module.stream_fns()
    tokens = jnp.asarray(tokens)
    if tokens.ndim == 1:
        tokens = tokens[None, :]
    B, T = tokens.shape
    resident = {k: v for k, v in params.items() if k != "layers"}
    layers_stacked = params["layers"]
    n_layers = int(jax.tree_util.tree_leaves(layers_stacked)[0].shape[0])
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    rng = jax.random.PRNGKey(0)

    j_embed = jax.jit(embed_fwd)
    j_layer = jax.jit(lambda p, h: layer_fwd(p, h, positions, rng, train=False))
    j_head = jax.jit(lambda r, h: head_loss(r, h, None))

    h = j_embed(resident, tokens)
    layer0 = jax.tree_util.tree_map(lambda x: x[0], layers_stacked)

    embed_cost = get_compiled_cost(j_embed, resident, tokens)["flops"]
    layer_cost = get_compiled_cost(j_layer, layer0, h)["flops"]
    head_cost = get_compiled_cost(j_head, resident, h)["flops"]
    embed_lat = _time_jitted(j_embed, resident, tokens, runs=runs)
    layer_lat = _time_jitted(j_layer, layer0, h, runs=runs)
    head_lat = _time_jitted(j_head, resident, h, runs=runs)

    embed_params = _tree_params(params.get("embed"))
    head_params = _tree_params(resident) - embed_params

    total_flops = embed_cost + n_layers * layer_cost + head_cost
    total_lat = embed_lat + n_layers * layer_lat + head_lat
    root = ModuleProfile(
        type(module).__name__, 0, _tree_params(params), total_flops, total_lat
    )
    root.children.append(ModuleProfile("embed", 1, embed_params, embed_cost, embed_lat))
    layers_node = ModuleProfile(
        "layers", 1, _tree_params(layers_stacked), n_layers * layer_cost,
        n_layers * layer_lat,
    )
    per_layer_params = _tree_params(layers_stacked) // max(n_layers, 1)
    for i in range(n_layers):
        layers_node.children.append(
            ModuleProfile(f"layers.{i}", 2, per_layer_params, layer_cost, layer_lat)
        )
    root.children.append(layers_node)
    root.children.append(ModuleProfile("head", 1, head_params, head_cost, head_lat))
    return root


def render_module_tree(root: ModuleProfile) -> str:
    """The reference's per-module printout: depth-indented rows of
    params, MACs, latency, and % of total (profiler.py:85-130)."""
    lines = []

    def pct(x, total):
        return f"{100.0 * x / total:.2f}%" if total else "0.00%"

    def walk(node: ModuleProfile):
        indent = "  " * node.depth
        lines.append(
            f"{indent}{node.name}: "
            f"{params_to_string(node.params)} params, "
            f"{macs_to_string(node.macs)}, "
            f"{duration_to_string(node.latency)}, "
            f"{pct(node.flops, root.flops)} flops, "
            f"{pct(node.latency, root.latency)} latency"
        )
        for c in node.children:
            walk(c)

    walk(root)
    return "\n".join(lines)


class FlopsProfiler:
    """Engine-attached profiler (reference profiler.py:28).

    Usage inside the engine (engine.forward wires this at
    ``flops_profiler.profile_step``): ``start_profile()`` → run the step →
    ``stop_profile()`` → ``print_model_profile(...)``.
    """

    def __init__(self, model=None, ds_engine=None):
        self.model = model
        self.ds_engine = ds_engine
        self.started = False
        self._t0 = None
        self.latency = 0.0
        self.cost: Dict[str, float] = {}

    def start_profile(self, ignore_list=None) -> None:  # noqa: ARG002
        self.started = True
        self._t0 = time.perf_counter()

    def stop_profile(self) -> None:
        if self._t0 is not None:
            self.latency = time.perf_counter() - self._t0
        if self.ds_engine is not None and getattr(self.ds_engine, "_jit_fwd_bwd", None) is not None:
            e = self.ds_engine
            try:
                if getattr(e, "_last_profile_args", None) is not None:
                    fn = getattr(e, "_profile_fn", None) or e._jit_fwd_bwd
                    self.cost = get_compiled_cost(fn, *e._last_profile_args)
            except Exception as ex:  # cost analysis is best-effort
                logger.debug(f"flops cost analysis unavailable: {ex}")

    def reset_profile(self) -> None:
        self.cost = {}
        self.latency = 0.0

    def end_profile(self) -> None:
        self.started = False

    # --- reference accessor surface --------------------------------------
    def get_total_flops(self, as_string: bool = False):
        flops = self.cost.get("flops", 0.0)
        return flops_to_string(flops) if as_string else flops

    def get_total_macs(self, as_string: bool = False):
        macs = self.cost.get("flops", 0.0) / 2
        return macs_to_string(macs) if as_string else macs

    def get_total_duration(self, as_string: bool = False):
        return duration_to_string(self.latency) if as_string else self.latency

    def get_total_params(self, as_string: bool = False):
        n = 0
        if self.ds_engine is not None:
            n = self.ds_engine.num_parameters()
        return params_to_string(n) if as_string else n

    def get_module_profile(self) -> Optional[ModuleProfile]:
        """Per-module tree for the engine's model (None when the engine is
        absent, uninitialized, or its module is not layer-streamable)."""
        e = self.ds_engine
        if e is None or not getattr(e, "_initialized", False):
            return None
        module = getattr(e, "module", None)
        if module is None or not hasattr(module, "stream_fns"):
            return None
        batch = getattr(e, "_last_batch", None)
        if batch is None:
            return None
        tokens = batch.get("input_ids") if hasattr(batch, "get") else batch[0]
        try:
            return get_module_profile(module, e.get_params(), tokens)
        except Exception as ex:  # best-effort, like the whole-program cost
            logger.debug(f"per-module profile unavailable: {ex}")
            return None

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1, detailed=True, output_file=None):  # noqa: ARG002
        flops = self.get_total_flops()
        latency = self.get_total_duration()
        lines = [
            "-------------------------- DeepSpeed Flops Profiler --------------------------",
            f"Profile step:                           {profile_step}",
            f"Params:                                 {self.get_total_params(as_string=True)}",
            f"Compiled step flops:                    {flops_to_string(flops)}",
            f"Bytes accessed:                         {_num_to_string(self.cost.get('bytes_accessed', 0.0))}B",
            f"Step latency:                           {duration_to_string(latency)}",
        ]
        if latency > 0 and flops > 0:
            lines.append(
                f"Achieved throughput:                    {flops_to_string(flops / latency)}/s"
            )
        if "peak_memory_bytes" in self.cost:
            lines.append(
                f"Peak compiled memory:                   {_num_to_string(self.cost['peak_memory_bytes'])}B"
            )
        if detailed:
            tree = self.get_module_profile()
            if tree is not None:
                lines.append("")
                lines.append("Per-module profile (params, MACs, latency, % of total):")
                lines.append(render_module_tree(tree))
        lines.append("-" * 79)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        else:
            print(text)


def get_model_profile(
    model: Callable,
    input_shape: Optional[Tuple] = None,
    args=None,
    kwargs=None,
    print_profile: bool = True,
    detailed: bool = True,  # noqa: ARG001
    warm_up: int = 1,
    as_string: bool = True,
    output_file: Optional[str] = None,  # noqa: ARG001
    ignore_modules=None,  # noqa: ARG001
):
    """Profile a standalone callable (reference :1039): returns
    (flops, macs, params) — params only when the callable carries a param
    tree as first arg."""
    import jax

    if args is None:
        if input_shape is not None:
            rs = np.random.RandomState(0)
            args = (rs.randn(*input_shape).astype(np.float32),)
        else:
            raise ValueError("specify input_shape or args")
    kwargs = kwargs or {}
    jitted = jax.jit(model)
    for _ in range(warm_up):
        jax.tree_util.tree_map(
            lambda x: getattr(x, "block_until_ready", lambda: x)(), jitted(*args, **kwargs)
        )
    t0 = time.perf_counter()
    out = jitted(*args, **kwargs)
    jax.tree_util.tree_map(lambda x: getattr(x, "block_until_ready", lambda: x)(), out)
    latency = time.perf_counter() - t0
    cost = get_compiled_cost(jitted, *args, **kwargs)
    flops = cost.get("flops", 0.0)
    macs = flops / 2
    params = 0
    if args and hasattr(args[0], "items"):
        params = sum(int(np.prod(np.shape(l))) for l in jax.tree_util.tree_leaves(args[0]))
    if print_profile:
        print(
            f"flops={flops_to_string(flops)} macs={macs_to_string(macs)} "
            f"params={params_to_string(params)} latency={duration_to_string(latency)}"
        )
    if as_string:
        return flops_to_string(flops), macs_to_string(macs), params_to_string(params)
    return flops, macs, params
