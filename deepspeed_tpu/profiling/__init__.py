"""Profiling (reference: ``deepspeed/profiling/``) + TPU-native compile
telemetry (``compile_telemetry`` — per-program trace/compile counters and the
persistent-compilation-cache opt-in)."""

from deepspeed_tpu.profiling.compile_telemetry import (  # noqa: F401
    CompileTelemetry,
    InstrumentedFunction,
    ProgramStats,
    configure_persistent_cache,
)
