"""Profiling (reference: ``deepspeed/profiling/``)."""
