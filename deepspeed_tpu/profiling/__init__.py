"""Profiling (reference: ``deepspeed/profiling/``) + TPU-native compile
telemetry (``compile_telemetry`` — per-program trace/compile counters and the
persistent-compilation-cache opt-in) + the unified tracing/metrics plane
(``tracer`` — step/request spans, metrics registry, Chrome-trace export,
flight recorder, observability hub)."""

from deepspeed_tpu.profiling.compile_telemetry import (  # noqa: F401
    CompileTelemetry,
    InstrumentedFunction,
    ProgramStats,
    configure_persistent_cache,
)
from deepspeed_tpu.profiling.tracer import (  # noqa: F401
    NULL_TRACER,
    Counter,
    FlightRecorder,
    Gauge,
    Histogram,
    MetricsRegistry,
    ObservabilityHub,
    Tracer,
)
