"""Compile/retrace telemetry for the engine's jitted programs.

The hot loop is a handful of long-lived jitted programs (fwd_bwd, step,
fused_step, fused_accum_step, eval); every unplanned retrace of one of them
costs a multi-second XLA compile on the CPU mesh and minutes through the
tunneled TPU compiler — and, accumulated, stale executables have wedged whole
test sessions (PERF.md round 5). This module makes both visible:

* ``CompileTelemetry.instrument(name, fn, **jit_kwargs)`` wraps ``jax.jit``
  so each named program counts traces (re-entries of the python function by
  the tracing machinery), cold dispatches (calls that triggered a trace —
  i.e. compiles, or persistent-cache loads), total dispatches, and the wall
  time spent in trace-triggering calls. The counters survive program
  rebuilds: re-instrumenting under the same name accumulates into the same
  record, so a retrace-regression guard can assert "≤1 compile across N
  steps" without caring when the engine rebuilt its callables.
* ``configure_persistent_cache`` opts into JAX's on-disk compilation cache so
  repeated runs (bench retries, restarted jobs) skip cold compiles entirely.

The wrapper forwards ``lower``/``eval_shape``/``clear_cache`` to the
underlying jitted callable, so AOT inspection (donation sets, cost analysis)
and explicit executable release keep working through it.

The registry is also the capture point for the static-analysis layer
(``deepspeed_tpu/analysis``): each cold dispatch records the abstract call
signature (shape/dtype/sharding per argument leaf — metadata survives
buffer donation), so the analysis passes can re-derive the exact lowered
and compiled program later without holding any live buffers, and the
retrace-cause differ can name the argument whose aval/sharding changed
between two traces of the same program.
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax

# per-program cap on retained trace signatures: enough for the retrace
# differ (consecutive pairs) without unbounded growth in resize loops
_TRACE_LOG_CAP = 8


def _abstract_leaf(x):
    """ShapeDtypeStruct stand-in for an array leaf; any non-array leaf
    (python scalar, None-in-dict, string) passes through verbatim so a
    re-trace sees exactly the original weak-typed value. Shardings are kept
    only for COMMITTED arrays — an uncommitted array does not constrain
    jit's placement, but a ShapeDtypeStruct carrying its current
    (single-device) sharding would, and the re-trace would then reject the
    mesh-sharded neighbors it originally composed with."""
    if isinstance(x, jax.Array):
        try:
            if getattr(x, "_committed", True):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        except Exception:
            pass
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    if hasattr(x, "shape") and hasattr(x, "dtype"):  # np.ndarray / np scalar
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


def describe_signature(args, kwargs) -> Dict[str, Dict[str, Any]]:
    """Flat {arg path: leaf description} for one call signature. Safe on
    donated (deleted) arrays — only metadata is read."""
    flat, _ = jax.tree_util.tree_flatten_with_path((args, kwargs or {}))
    out = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sharding = getattr(leaf, "sharding", None)
            out[key] = {
                "shape": tuple(leaf.shape),
                "dtype": str(leaf.dtype),
                "sharding": None if sharding is None else str(sharding),
            }
        else:
            out[key] = {"value": repr(leaf)[:80], "type": type(leaf).__name__}
    return out


@dataclass
class ProgramStats:
    """Counters for one named jitted program."""

    name: str
    traces: int = 0
    compiles: int = 0  # dispatches that triggered a trace (cold dispatches)
    dispatches: int = 0
    compile_seconds: float = 0.0  # wall time of trace-triggering dispatches
    invalidations: int = 0  # explicit clear_cache() calls
    first_compile_at: Optional[float] = field(default=None, repr=False)
    # one entry per cold dispatch: the flat signature description the
    # retrace-cause differ consumes (analysis/report.py)
    trace_log: List[Dict[str, Any]] = field(default_factory=list, repr=False)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "traces": self.traces,
            "compiles": self.compiles,
            "dispatches": self.dispatches,
            "compile_seconds": round(self.compile_seconds, 4),
            "invalidations": self.invalidations,
        }

    def log_trace(self, signature: Dict[str, Any]) -> None:
        self.trace_log.append(signature)
        if len(self.trace_log) > _TRACE_LOG_CAP:
            del self.trace_log[0]


class InstrumentedFunction:
    """A ``jax.jit`` callable that feeds a shared ``ProgramStats`` record.

    A dispatch that re-enters the python function (trace counter moved) is a
    cold dispatch: trace + compile (or persistent-cache load) + first run —
    its whole wall time is charged to ``compile_seconds``. Warm dispatches
    only bump ``dispatches``. ``lower``/``eval_shape`` trace without
    dispatching, so they bump ``traces`` but never ``compiles``.
    """

    def __init__(
        self,
        fn: Callable,
        stats: ProgramStats,
        jit_kwargs: Dict[str, Any],
        on_compile: Optional[Callable[[str], None]] = None,
    ):
        self._stats = stats
        self._on_compile = on_compile
        # latest cold-dispatch signature as abstract pytrees: enough to
        # re-trace/lower/compile the program for analysis without pinning
        # any device buffer (donated args are captured as metadata)
        self._abstract_signature = None

        def traced(*args, **kwargs):
            stats.traces += 1
            return fn(*args, **kwargs)

        traced.__name__ = getattr(fn, "__name__", stats.name)
        try:
            self._jitted = jax.jit(traced, **jit_kwargs)
        except TypeError:
            # older jax: jit has no compiler_options (the engine passes XLA
            # latency-hiding-scheduler flags through it when available) —
            # run unscheduled rather than failing the program build
            if "compiler_options" not in jit_kwargs:
                raise
            jit_kwargs = {
                k: v for k, v in jit_kwargs.items() if k != "compiler_options"
            }
            self._jitted = jax.jit(traced, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        st = self._stats
        st.dispatches += 1
        traces_before = st.traces
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        if st.traces > traces_before:
            st.compiles += 1
            st.compile_seconds += time.perf_counter() - t0
            if st.first_compile_at is None:
                st.first_compile_at = time.time()
            # cold dispatch: record the signature for the analysis layer.
            # Donated inputs are already consumed, but shape/dtype/sharding
            # metadata outlives the buffer, so the capture is free of
            # device memory. Best-effort: telemetry must never fail a step.
            try:
                self._abstract_signature = jax.tree_util.tree_map(
                    _abstract_leaf, (args, kwargs)
                )
                st.log_trace(describe_signature(args, kwargs))
            except Exception:
                pass
            if self._on_compile is not None:
                self._on_compile(st.name)
        return out

    # --- analysis surface ----------------------------------------------
    @property
    def abstract_signature(self):
        """(args, kwargs) pytrees of ShapeDtypeStructs (+ verbatim
        non-array leaves) from the latest cold dispatch, or None if the
        program has never dispatched."""
        return self._abstract_signature

    def trace_abstract(self):
        """Re-trace the program from the captured cold-dispatch signature.
        Raises if the program has never dispatched."""
        if self._abstract_signature is None:
            raise ValueError(
                f"program {self._stats.name!r} has no captured signature "
                "(never dispatched through this wrapper)"
            )
        args, kwargs = self._abstract_signature
        return self._jitted.trace(*args, **kwargs)

    # --- AOT / lifecycle pass-throughs ---------------------------------
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def eval_shape(self, *args, **kwargs):
        return self._jitted.eval_shape(*args, **kwargs)

    def clear_cache(self) -> None:
        """Release this program's compiled executables (the fix for the
        PERF.md mid-suite wedge: rebinding the attribute alone leaves the
        stale executable alive in jit's cache)."""
        self._stats.invalidations += 1
        self._jitted.clear_cache()

    def cache_size(self) -> int:
        try:
            return int(self._jitted._cache_size())
        except Exception:
            return -1  # jit internals moved; telemetry stays best-effort

    @property
    def stats(self) -> ProgramStats:
        return self._stats


class CompileTelemetry:
    """Registry of named instrumented programs (one per engine)."""

    _uids = itertools.count()

    def __init__(self):
        self._programs: Dict[str, ProgramStats] = {}
        # latest wrapper per name: the analysis passes re-derive lowered/
        # compiled artifacts through it (only the newest build matters —
        # stale wrappers are dropped so their executables can be GC'd)
        self._fns: Dict[str, InstrumentedFunction] = {}
        # optional hook fired (with the program name) after each cold
        # dispatch completes — the engines use it for analysis.verify
        self.on_compile: Optional[Callable[[str], None]] = None
        # process-unique, never-recycled id: module-level program caches
        # (inference/decode.py) key compiled callables on it — ``id(self)``
        # could alias a dead registry at a recycled address
        self.uid = next(CompileTelemetry._uids)

    def instrument(self, name: str, fn: Callable, **jit_kwargs) -> InstrumentedFunction:
        """``jax.jit(fn, **jit_kwargs)`` with counters under ``name``.
        Re-instrumenting an existing name (engine rebuild) accumulates into
        the same record."""
        stats = self._programs.setdefault(name, ProgramStats(name))
        wrapper = InstrumentedFunction(
            fn, stats, jit_kwargs, on_compile=self._fire_on_compile
        )
        self._fns[name] = wrapper
        return wrapper

    def _fire_on_compile(self, name: str) -> None:
        # late-bound: engines set self.on_compile after instrument() calls
        if self.on_compile is not None:
            self.on_compile(name)

    def programs(self) -> Dict[str, InstrumentedFunction]:
        """{name: latest InstrumentedFunction} — the analysis layer's view."""
        return dict(self._fns)

    def program_stats(self, name: str) -> Optional[ProgramStats]:
        return self._programs.get(name)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-program counter snapshot: {name: {traces, compiles,
        dispatches, compile_seconds, invalidations}}."""
        return {name: s.snapshot() for name, s in sorted(self._programs.items())}

    def totals(self) -> Dict[str, Any]:
        """Aggregate counters over every instrumented program."""
        out = {"traces": 0, "compiles": 0, "dispatches": 0, "compile_seconds": 0.0}
        for s in self._programs.values():
            out["traces"] += s.traces
            out["compiles"] += s.compiles
            out["dispatches"] += s.dispatches
            out["compile_seconds"] += s.compile_seconds
        out["compile_seconds"] = round(out["compile_seconds"], 4)
        return out

    def reset(self) -> None:
        self._programs.clear()
        self._fns.clear()


def configure_persistent_cache(cache_dir: str, min_compile_secs: float = 0.0) -> bool:
    """Opt into JAX's persistent compilation cache at ``cache_dir``.

    Process-global (jax.config): every jitted program whose compile takes
    longer than ``min_compile_secs`` is written to disk and reloaded on the
    next run with the same program — a restarted job or bench retry skips
    its cold compiles. Returns False when this jax has no such config
    (older releases), leaving the run uncached rather than failing it.
    """
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_secs)
        )
    except Exception:
        return False
    return True
