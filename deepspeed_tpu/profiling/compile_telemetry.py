"""Compile/retrace telemetry for the engine's jitted programs.

The hot loop is a handful of long-lived jitted programs (fwd_bwd, step,
fused_step, fused_accum_step, eval); every unplanned retrace of one of them
costs a multi-second XLA compile on the CPU mesh and minutes through the
tunneled TPU compiler — and, accumulated, stale executables have wedged whole
test sessions (PERF.md round 5). This module makes both visible:

* ``CompileTelemetry.instrument(name, fn, **jit_kwargs)`` wraps ``jax.jit``
  so each named program counts traces (re-entries of the python function by
  the tracing machinery), cold dispatches (calls that triggered a trace —
  i.e. compiles, or persistent-cache loads), total dispatches, and the wall
  time spent in trace-triggering calls. The counters survive program
  rebuilds: re-instrumenting under the same name accumulates into the same
  record, so a retrace-regression guard can assert "≤1 compile across N
  steps" without caring when the engine rebuilt its callables.
* ``configure_persistent_cache`` opts into JAX's on-disk compilation cache so
  repeated runs (bench retries, restarted jobs) skip cold compiles entirely.

The wrapper forwards ``lower``/``eval_shape``/``clear_cache`` to the
underlying jitted callable, so AOT inspection (donation sets, cost analysis)
and explicit executable release keep working through it.
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import jax


@dataclass
class ProgramStats:
    """Counters for one named jitted program."""

    name: str
    traces: int = 0
    compiles: int = 0  # dispatches that triggered a trace (cold dispatches)
    dispatches: int = 0
    compile_seconds: float = 0.0  # wall time of trace-triggering dispatches
    invalidations: int = 0  # explicit clear_cache() calls
    first_compile_at: Optional[float] = field(default=None, repr=False)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "traces": self.traces,
            "compiles": self.compiles,
            "dispatches": self.dispatches,
            "compile_seconds": round(self.compile_seconds, 4),
            "invalidations": self.invalidations,
        }


class InstrumentedFunction:
    """A ``jax.jit`` callable that feeds a shared ``ProgramStats`` record.

    A dispatch that re-enters the python function (trace counter moved) is a
    cold dispatch: trace + compile (or persistent-cache load) + first run —
    its whole wall time is charged to ``compile_seconds``. Warm dispatches
    only bump ``dispatches``. ``lower``/``eval_shape`` trace without
    dispatching, so they bump ``traces`` but never ``compiles``.
    """

    def __init__(self, fn: Callable, stats: ProgramStats, jit_kwargs: Dict[str, Any]):
        self._stats = stats

        def traced(*args, **kwargs):
            stats.traces += 1
            return fn(*args, **kwargs)

        traced.__name__ = getattr(fn, "__name__", stats.name)
        self._jitted = jax.jit(traced, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        st = self._stats
        st.dispatches += 1
        traces_before = st.traces
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        if st.traces > traces_before:
            st.compiles += 1
            st.compile_seconds += time.perf_counter() - t0
            if st.first_compile_at is None:
                st.first_compile_at = time.time()
        return out

    # --- AOT / lifecycle pass-throughs ---------------------------------
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def eval_shape(self, *args, **kwargs):
        return self._jitted.eval_shape(*args, **kwargs)

    def clear_cache(self) -> None:
        """Release this program's compiled executables (the fix for the
        PERF.md mid-suite wedge: rebinding the attribute alone leaves the
        stale executable alive in jit's cache)."""
        self._stats.invalidations += 1
        self._jitted.clear_cache()

    def cache_size(self) -> int:
        try:
            return int(self._jitted._cache_size())
        except Exception:
            return -1  # jit internals moved; telemetry stays best-effort

    @property
    def stats(self) -> ProgramStats:
        return self._stats


class CompileTelemetry:
    """Registry of named instrumented programs (one per engine)."""

    _uids = itertools.count()

    def __init__(self):
        self._programs: Dict[str, ProgramStats] = {}
        # process-unique, never-recycled id: module-level program caches
        # (inference/decode.py) key compiled callables on it — ``id(self)``
        # could alias a dead registry at a recycled address
        self.uid = next(CompileTelemetry._uids)

    def instrument(self, name: str, fn: Callable, **jit_kwargs) -> InstrumentedFunction:
        """``jax.jit(fn, **jit_kwargs)`` with counters under ``name``.
        Re-instrumenting an existing name (engine rebuild) accumulates into
        the same record."""
        stats = self._programs.setdefault(name, ProgramStats(name))
        return InstrumentedFunction(fn, stats, jit_kwargs)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-program counter snapshot: {name: {traces, compiles,
        dispatches, compile_seconds, invalidations}}."""
        return {name: s.snapshot() for name, s in sorted(self._programs.items())}

    def totals(self) -> Dict[str, Any]:
        """Aggregate counters over every instrumented program."""
        out = {"traces": 0, "compiles": 0, "dispatches": 0, "compile_seconds": 0.0}
        for s in self._programs.values():
            out["traces"] += s.traces
            out["compiles"] += s.compiles
            out["dispatches"] += s.dispatches
            out["compile_seconds"] += s.compile_seconds
        out["compile_seconds"] = round(out["compile_seconds"], 4)
        return out

    def reset(self) -> None:
        self._programs.clear()


def configure_persistent_cache(cache_dir: str, min_compile_secs: float = 0.0) -> bool:
    """Opt into JAX's persistent compilation cache at ``cache_dir``.

    Process-global (jax.config): every jitted program whose compile takes
    longer than ``min_compile_secs`` is written to disk and reloaded on the
    next run with the same program — a restarted job or bench retry skips
    its cold compiles. Returns False when this jax has no such config
    (older releases), leaving the run uncached rather than failing it.
    """
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_secs)
        )
    except Exception:
        return False
    return True
