"""Autotuning (reference: ``deepspeed/autotuning/``)."""

from deepspeed_tpu.autotuning.autotuner import (
    Autotuner,
    GridSearchTuner,
    ModelBasedTuner,
    RandomTuner,
    run_autotuning,
)
from deepspeed_tpu.autotuning.config_templates import (
    STAGE_TEMPLATES,
    candidate_configs,
    merge_config,
    template_for_stage,
)
from deepspeed_tpu.autotuning.scheduler import (
    Experiment,
    ExpStatus,
    ResourceManager,
    SubprocessTrialRunner,
)
