"""Autotuning (reference: ``deepspeed/autotuning/``)."""

from deepspeed_tpu.autotuning.autotuner import (
    Autotuner,
    GridSearchTuner,
    ModelBasedTuner,
    RandomTuner,
    run_autotuning,
)
