"""Experiment scheduler over a resource pool (reference:
``deepspeed/autotuning/scheduler.py`` ``ResourceManager``).

The reference schedules tuning experiments across reserved node groups via
ssh; here a resource is any experiment-executor slot (on one TPU host:
usually 1 — trials share the chip serially; in a pod: one slot per slice).
Experiments carry QUEUED → RUNNING → DONE/FAILED state, results collect as
they finish, and the caller's tuner drains the queue in arrival order.
"""

from __future__ import annotations

import enum
import time
from typing import Any, Callable, Dict, List, Optional


class ExpStatus(str, enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"


class Experiment:
    _next_id = 0

    def __init__(self, config: Dict):
        Experiment._next_id += 1
        self.exp_id = Experiment._next_id
        self.config = config
        self.status = ExpStatus.QUEUED
        self.result: Optional[Dict] = None
        self.error: Optional[str] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None


class ResourceManager:
    """Run experiments over ``num_slots`` executor slots.

    ``run_fn(config) -> result_dict | None`` executes one experiment (the
    autotuner's ``run_trial``); exceptions / None mark the experiment
    FAILED. With one slot this is the single-host serial flow; more slots
    round-robin (a pod-slice pool would pass per-slice executors)."""

    def __init__(self, run_fn: Callable[[Dict], Optional[Dict]], num_slots: int = 1):
        self.run_fn = run_fn
        self.num_slots = max(1, num_slots)
        self.experiments: List[Experiment] = []

    def schedule(self, config: Dict) -> Experiment:
        exp = Experiment(config)
        self.experiments.append(exp)
        return exp

    def schedule_all(self, configs: List[Dict]) -> List[Experiment]:
        return [self.schedule(c) for c in configs]

    def _run_one(self, exp: Experiment) -> None:
        exp.status = ExpStatus.RUNNING
        exp.start_time = time.perf_counter()
        try:
            result = self.run_fn(exp.config)
        except Exception as e:  # an exploding trial must not kill the sweep
            exp.status = ExpStatus.FAILED
            exp.error = f"{type(e).__name__}: {e}"
            exp.end_time = time.perf_counter()
            return
        exp.end_time = time.perf_counter()
        if result is None:
            exp.status = ExpStatus.FAILED
        else:
            exp.status = ExpStatus.DONE
            exp.result = result

    def run(self) -> List[Experiment]:
        """Drain the queue. With >1 slot, experiments run concurrently in a
        thread pool (each slot's executor owns its device resources)."""
        queued = [e for e in self.experiments if e.status == ExpStatus.QUEUED]
        if self.num_slots == 1:
            for exp in queued:
                self._run_one(exp)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=self.num_slots) as pool:
                list(pool.map(self._run_one, queued))
        return self.experiments

    # --- reporting -------------------------------------------------------
    def finished(self) -> List[Experiment]:
        return [e for e in self.experiments if e.status in (ExpStatus.DONE, ExpStatus.FAILED)]

    def successful(self) -> List[Experiment]:
        return [e for e in self.experiments if e.status == ExpStatus.DONE]

    def best(self, key: Callable[[Dict], Any], maximize: bool = True) -> Optional[Experiment]:
        done = self.successful()
        if not done:
            return None
        pick = max if maximize else min
        return pick(done, key=lambda e: key(e.result))

    def summary(self) -> List[Dict]:
        return [
            {
                "exp_id": e.exp_id,
                "status": e.status.value,
                "stage": e.config.get("zero_optimization", {}).get("stage"),
                "micro_batch": e.config.get("train_micro_batch_size_per_gpu"),
                "result": e.result,
                "error": e.error,
                "elapsed_s": (
                    (e.end_time - e.start_time)
                    if e.start_time is not None and e.end_time is not None
                    else None
                ),
            }
            for e in self.experiments
        ]
