"""Experiment scheduler over a resource pool (reference:
``deepspeed/autotuning/scheduler.py`` ``ResourceManager``).

The reference schedules tuning experiments across reserved node groups via
ssh; here a resource is any experiment-executor slot (on one TPU host:
usually 1 — trials share the chip serially; in a pod: one slot per slice).
Experiments carry QUEUED → RUNNING → DONE/FAILED state, results collect as
they finish, and the caller's tuner drains the queue in arrival order.

``SubprocessTrialRunner`` is the hardware-session executor (reference
``run_job``'s per-experiment launch): each trial runs in its own killable
process, so an HBM OOM or a stalled tunneled backend fails ONE experiment,
not the sweep.
"""

from __future__ import annotations

import enum
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional


class ExpStatus(str, enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"


class Experiment:
    _next_id = 0

    def __init__(self, config: Dict):
        Experiment._next_id += 1
        self.exp_id = Experiment._next_id
        self.config = config
        self.status = ExpStatus.QUEUED
        self.result: Optional[Dict] = None
        self.error: Optional[str] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None


class SubprocessTrialRunner:
    """Callable trial executor that spawns ``trial_runner`` per experiment.

    ``user_script`` follows the ``deepspeed --autotuning`` contract
    (defines model_factory / batch_factory / base_config). ``timeout_s``
    kills the whole process group — the tunneled TPU backend can stall for
    minutes, and a stalled trial must not eat the session. ``env`` overrides
    the child environment (e.g. JAX_PLATFORMS=cpu for harness tests)."""

    def __init__(
        self,
        user_script: str,
        trial_steps: int = 5,
        warmup_steps: int = 2,
        timeout_s: float = 600.0,
        env: Optional[Dict[str, str]] = None,
        log_path: Optional[str] = None,
    ):
        self.user_script = user_script
        self.trial_steps = trial_steps
        self.warmup_steps = warmup_steps
        self.timeout_s = timeout_s
        self.env = env
        self.log_path = log_path or os.devnull

    def __call__(self, config: Dict) -> Optional[Dict]:
        with tempfile.TemporaryDirectory(prefix="ds_tune_") as tmp:
            cfg_path = os.path.join(tmp, "exp.json")
            out_path = os.path.join(tmp, "result.json")
            with open(cfg_path, "w") as f:
                json.dump(config, f, default=str)
            cmd = [
                sys.executable,
                "-m",
                "deepspeed_tpu.autotuning.trial_runner",
                "--script",
                self.user_script,
                "--config",
                cfg_path,
                "--out",
                out_path,
                "--trial-steps",
                str(self.trial_steps),
                "--warmup-steps",
                str(self.warmup_steps),
            ]
            env = dict(os.environ)
            if self.env:
                env.update(self.env)
            with open(self.log_path, "ab") as log:
                proc = subprocess.Popen(
                    cmd, stdout=log, stderr=subprocess.STDOUT, start_new_session=True, env=env
                )
                try:
                    proc.wait(timeout=self.timeout_s)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(os.getpgid(proc.pid), 9)
                    except (ProcessLookupError, PermissionError):
                        proc.kill()
                    proc.wait()
            # the result file, not the rc, is the success signal — checked
            # on the timeout path too: a child that wrote it and then hung
            # in backend teardown still measured something
            if not os.path.exists(out_path):
                return None
            try:
                with open(out_path) as f:
                    return json.load(f)
            except Exception:
                return None


class ResourceManager:
    """Run experiments over ``num_slots`` executor slots.

    ``run_fn(config) -> result_dict | None`` executes one experiment (the
    autotuner's ``run_trial``); exceptions / None mark the experiment
    FAILED. With one slot this is the single-host serial flow; more slots
    round-robin (a pod-slice pool would pass per-slice executors)."""

    def __init__(self, run_fn: Callable[[Dict], Optional[Dict]], num_slots: int = 1):
        self.run_fn = run_fn
        self.num_slots = max(1, num_slots)
        self.experiments: List[Experiment] = []

    def schedule(self, config: Dict) -> Experiment:
        exp = Experiment(config)
        self.experiments.append(exp)
        return exp

    def schedule_all(self, configs: List[Dict]) -> List[Experiment]:
        return [self.schedule(c) for c in configs]

    def _run_one(self, exp: Experiment) -> None:
        exp.status = ExpStatus.RUNNING
        exp.start_time = time.perf_counter()
        try:
            result = self.run_fn(exp.config)
        except Exception as e:  # an exploding trial must not kill the sweep
            exp.status = ExpStatus.FAILED
            exp.error = f"{type(e).__name__}: {e}"
            exp.end_time = time.perf_counter()
            return
        exp.end_time = time.perf_counter()
        if result is None:
            exp.status = ExpStatus.FAILED
        else:
            exp.status = ExpStatus.DONE
            exp.result = result

    def run(self) -> List[Experiment]:
        """Drain the queue. With >1 slot, experiments run concurrently in a
        thread pool (each slot's executor owns its device resources)."""
        queued = [e for e in self.experiments if e.status == ExpStatus.QUEUED]
        if self.num_slots == 1:
            for exp in queued:
                self._run_one(exp)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=self.num_slots) as pool:
                list(pool.map(self._run_one, queued))
        return self.experiments

    # --- reporting -------------------------------------------------------
    def finished(self) -> List[Experiment]:
        return [e for e in self.experiments if e.status in (ExpStatus.DONE, ExpStatus.FAILED)]

    def successful(self) -> List[Experiment]:
        return [e for e in self.experiments if e.status == ExpStatus.DONE]

    def best(self, key: Callable[[Dict], Any], maximize: bool = True) -> Optional[Experiment]:
        done = self.successful()
        if not done:
            return None
        pick = max if maximize else min
        return pick(done, key=lambda e: key(e.result))

    def summary(self) -> List[Dict]:
        return [
            {
                "exp_id": e.exp_id,
                "status": e.status.value,
                "stage": e.config.get("zero_optimization", {}).get("stage"),
                "micro_batch": e.config.get("train_micro_batch_size_per_gpu"),
                "result": e.result,
                "error": e.error,
                "elapsed_s": (
                    (e.end_time - e.start_time)
                    if e.start_time is not None and e.end_time is not None
                    else None
                ),
            }
            for e in self.experiments
        ]
