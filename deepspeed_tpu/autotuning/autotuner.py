"""Autotuner.

Counterpart of the reference's ``Autotuner``
(``deepspeed/autotuning/autotuner.py:42``): profile the model, derive which
ZeRO stages fit memory (``get_instantiation_memory_required_per_gpu``
reference :278), generate a candidate-config grid, run short trials, pick
the best by throughput/latency (``autotuning_metric``).

TPU deltas: trials run in-process by default (one jit cache per trial; the
reference schedules separate jobs because CUDA state is poisoned per
process — XLA recompiles cleanly), with ``isolation="subprocess"`` for
hardware sessions (reference ``scheduler.run_job`` parity: a killable
process per experiment so an OOM or tunnel stall fails one trial, not the
sweep). Memory feasibility uses the analytic ZeRO estimator plus the
compiled step's own memory analysis when available.
"""

from __future__ import annotations

import itertools
import json
import os
import random as _random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.zero.partition import estimate_zero_memory
from deepspeed_tpu.utils.logging import logger

DEFAULT_MICRO_BATCHES = [1, 2, 4, 8, 16]
DEFAULT_STAGES = [0, 1, 2, 3]

AUTOTUNING_METRIC_THROUGHPUT = "throughput"
AUTOTUNING_METRIC_LATENCY = "latency"


class BaseTuner:
    """(reference autotuning/tuner/base_tuner.py)"""

    def __init__(self, exps: List[Dict]):
        self.all_exps = list(exps)

    def next_batch(self, sample_size: int) -> List[Dict]:
        raise NotImplementedError

    def has_next(self) -> bool:
        return bool(self.all_exps)


class GridSearchTuner(BaseTuner):
    """Exhaustive order (reference tuner/index_based_tuner.py)."""

    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class RandomTuner(BaseTuner):
    """Random order (reference tuner/index_based_tuner.py RandomTuner)."""

    def __init__(self, exps: List[Dict], seed: int = 0):
        super().__init__(exps)
        _random.Random(seed).shuffle(self.all_exps)

    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class ModelBasedTuner(BaseTuner):
    """Cost-model-guided order (reference tuner/model_based_tuner.py):
    candidates sorted by predicted per-chip memory headroom (larger micro
    batches first among feasible — the throughput prior)."""

    def __init__(self, exps: List[Dict], hbm_bytes: int, n_params: int, dp: int):
        def score(exp):
            zc = exp["zero_optimization"]["stage"]
            mem = estimate_zero_memory(n_params, zc, dp)["total_bytes"]
            headroom = hbm_bytes - mem
            return (headroom < 0, -exp["train_micro_batch_size_per_gpu"], zc)

        super().__init__(sorted(exps, key=score))

    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class Autotuner:
    def __init__(
        self,
        model_factory: Callable[[], Any],
        base_config: Dict,
        batch_factory: Callable[[int], Any],
        micro_batches: Optional[List[int]] = None,
        stages: Optional[List[int]] = None,
        metric: str = AUTOTUNING_METRIC_THROUGHPUT,
        tuner_type: str = "gridsearch",
        trial_steps: int = 5,
        warmup_steps: int = 2,
        max_trials: int = 50,
        hbm_bytes: int = 16 * 2**30,
        isolation: str = "in_process",
        user_script: Optional[str] = None,
        trial_timeout_s: float = 600.0,
        session_dir: Optional[str] = None,
        trial_env: Optional[Dict[str, str]] = None,
        num_devices: Optional[int] = None,
    ):
        if isolation not in ("in_process", "subprocess"):
            raise ValueError(f"isolation={isolation!r} (want in_process|subprocess)")
        if isolation == "subprocess" and not user_script:
            raise ValueError(
                "subprocess isolation needs user_script (the file defining "
                "model_factory/batch_factory/base_config for the child)"
            )
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        self.batch_factory = batch_factory
        self.micro_batches = micro_batches or DEFAULT_MICRO_BATCHES
        self.stages = stages or DEFAULT_STAGES
        self.metric = metric
        self.tuner_type = tuner_type
        self.trial_steps = trial_steps
        self.warmup_steps = warmup_steps
        self.max_trials = max_trials
        self.hbm_bytes = hbm_bytes
        self.isolation = isolation
        self.user_script = user_script
        self.trial_timeout_s = trial_timeout_s
        self.session_dir = session_dir
        self.trial_env = trial_env
        self.num_devices = num_devices
        self._model_info: Optional[Dict[str, Any]] = None
        self.results: List[Dict] = []

    # --- model info (reference model_info_profile_run :663) ---------------
    def model_info(self) -> Dict[str, Any]:
        """Parameter count via ``eval_shape`` with a ShapeDtypeStruct rng —
        fully abstract, so NO backend is initialized: in subprocess mode the
        parent must never claim the chip the trial children need. Memoized:
        generate_experiments and the model-based tuner both consult it."""
        if getattr(self, "_model_info", None) is not None:
            return self._model_info
        import jax
        import jax.numpy as jnp

        model = self.model_factory()
        batch = self.batch_factory(1)
        rng_shape = jax.ShapeDtypeStruct((2,), jnp.uint32)
        shapes = jax.eval_shape(
            lambda r, b: model.init(r, b) if hasattr(model, "init") else model[0](r, b),
            rng_shape,
            batch,
        )
        n = sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes))
        self._model_info = {"num_params": n}
        return self._model_info

    def _device_count(self) -> int:
        """dp width for the memory gate. In-process: the live backend.
        Subprocess mode: probe in a killable child — ``jax.devices()`` in
        the parent would BOTH lock the chip against the trial children and
        hang the session on a stalled tunnel."""
        if self.num_devices:
            return self.num_devices
        if self.isolation == "subprocess":
            import subprocess
            import sys

            # probe under the SAME env the trial children get — a cpu-forced
            # harness run must not gate memory on the hardware device count
            env = dict(os.environ)
            if self.trial_env:
                env.update(self.trial_env)
            try:
                out = subprocess.run(
                    [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
                    capture_output=True,
                    timeout=120,
                    text=True,
                    env=env,
                )
                self.num_devices = max(1, int(out.stdout.strip().splitlines()[-1]))
            except Exception:
                logger.warning("device-count probe failed; memory-gating for 1 device")
                self.num_devices = 1
            return self.num_devices
        import jax

        self.num_devices = len(jax.devices())
        return self.num_devices

    # --- candidate grid ---------------------------------------------------
    def generate_experiments(self) -> List[Dict]:
        """(stage, micro) sweep with the per-stage tuning templates applied
        (reference ``config_templates/``), memory-gated per candidate."""
        from deepspeed_tpu.autotuning.config_templates import candidate_configs

        info = self.model_info()
        n_params = info["num_params"]
        dp = self._device_count()
        exps = []
        for cfg in candidate_configs(self.base_config, self.stages, self.micro_batches):
            stage = cfg["zero_optimization"]["stage"]
            mem = estimate_zero_memory(n_params, stage, dp)["total_bytes"]
            if mem > self.hbm_bytes:
                logger.debug(f"skip stage={stage} (needs {mem/2**30:.1f} GiB)")
                continue
            exps.append(cfg)
        return exps

    def _make_tuner(self, exps: List[Dict]) -> BaseTuner:
        if self.tuner_type == "random":
            return RandomTuner(exps)
        if self.tuner_type == "model_based":
            info = self.model_info()
            return ModelBasedTuner(
                exps, self.hbm_bytes, info["num_params"], self._device_count()
            )
        return GridSearchTuner(exps)

    # --- trials -----------------------------------------------------------
    def run_trial(self, config: Dict) -> Optional[Dict]:
        import jax

        import deepspeed_tpu as ds
        import deepspeed_tpu.parallel.mesh as mesh_mod

        mesh_mod.reset_topology()
        micro = config["train_micro_batch_size_per_gpu"]
        try:
            engine, _, _, _ = ds.initialize(
                model=self.model_factory(), config=config, dist_init_required=False
            )
            batch = self.batch_factory(micro * engine.data_parallel_world_size())
            for _ in range(self.warmup_steps):
                loss = engine(batch)
                engine.backward(loss)
                engine.step()
            jax.device_get(loss)
            t0 = time.perf_counter()
            for _ in range(self.trial_steps):
                loss = engine(batch)
                engine.backward(loss)
                engine.step()
            jax.device_get(loss)
            dt = (time.perf_counter() - t0) / self.trial_steps
        except Exception as e:
            logger.warning(f"trial failed for {config.get('zero_optimization')}, mb={micro}: {e}")
            return None
        samples_per_sec = micro * engine.data_parallel_world_size() / dt
        return {
            "config": config,
            "latency_s": dt,
            "throughput_samples_per_s": samples_per_sec,
        }

    def _trial_fn(self):
        """Per-experiment executor: in-process (fast; harness/CI) or the
        reference-style isolated subprocess (hardware sessions — an OOM or
        a stalled tunneled backend fails one experiment, not the sweep)."""
        if self.isolation == "subprocess":
            from deepspeed_tpu.autotuning.scheduler import SubprocessTrialRunner

            log_path = (
                os.path.join(self.session_dir, "trials.log") if self.session_dir else None
            )
            return SubprocessTrialRunner(
                self.user_script,
                trial_steps=self.trial_steps,
                warmup_steps=self.warmup_steps,
                timeout_s=self.trial_timeout_s,
                env=self.trial_env,
                log_path=log_path,
            )
        return self.run_trial

    def _record_session(self) -> None:
        """Persist the tuning session (reference writes per-exp dirs under
        ``autotuning_exps/``): one summary json + the best config."""
        if not self.session_dir:
            return
        os.makedirs(self.session_dir, exist_ok=True)
        with open(os.path.join(self.session_dir, "session_summary.json"), "w") as f:
            json.dump(self.scheduler.summary(), f, indent=2, default=str)

    def tune(self) -> Optional[Dict]:
        from deepspeed_tpu.autotuning.scheduler import ResourceManager

        if self.session_dir:
            os.makedirs(self.session_dir, exist_ok=True)
        exps = self.generate_experiments()
        logger.info(f"autotuning over {len(exps)} candidate configs")
        tuner = self._make_tuner(exps)
        # the scheduler owns execution/status; the tuner owns the visit order
        self.scheduler = ResourceManager(self._trial_fn(), num_slots=1)
        trials = 0
        while tuner.has_next() and trials < self.max_trials:
            batch = tuner.next_batch(1)
            self.scheduler.schedule_all(batch)
            trials += len(batch)
        for exp in self.scheduler.run():
            if exp.result is not None:
                self.results.append(exp.result)
        self._record_session()
        if not self.results:
            return None
        if self.metric == AUTOTUNING_METRIC_LATENCY:
            best = min(self.results, key=lambda r: r["latency_s"])
        else:
            best = max(self.results, key=lambda r: r["throughput_samples_per_s"])
        logger.info(
            f"autotuning best: stage={best['config']['zero_optimization']['stage']} "
            f"micro={best['config']['train_micro_batch_size_per_gpu']} "
            f"({best['throughput_samples_per_s']:.1f} samples/s)"
        )
        if self.session_dir:
            with open(os.path.join(self.session_dir, "best_config.json"), "w") as f:
                json.dump(best, f, indent=2, default=str)
        return best


def load_user_script(path: str) -> Dict[str, Any]:
    """Exec the tuning user script and validate its contract — shared by the
    CLI entry and the subprocess trial runner so both fail with the same
    diagnostic instead of a bare KeyError."""
    namespace: Dict[str, Any] = {}
    with open(path) as f:
        code = f.read()
    exec(compile(code, path, "exec"), namespace)  # noqa: S102
    required = ("model_factory", "batch_factory", "base_config")
    if not all(k in namespace for k in required):
        raise RuntimeError(
            f"autotuning requires the script to define {required} "
            "(see deepspeed_tpu.autotuning.Autotuner)"
        )
    return namespace


def run_autotuning(args) -> int:
    """CLI entry (reference runner.py:360): the user script is expected to
    define ``model_factory``/``batch_factory``/``base_config``; exec it and
    tune."""
    namespace = load_user_script(args.user_script)
    # session dir: the ds config's autotuning.results_dir when set
    # (reference AUTOTUNING_RESULTS_DIR), else ./autotuning_results
    session_dir = (
        (namespace["base_config"].get("autotuning") or {}).get("results_dir")
        or "autotuning_results"
    )
    tuner = Autotuner(
        namespace["model_factory"],
        namespace["base_config"],
        namespace["batch_factory"],
        # CLI sessions are hardware sessions: reference-style isolated
        # trials + a persisted session record
        isolation="subprocess",
        user_script=args.user_script,
        session_dir=session_dir,
    )
    best = tuner.tune()
    if best is None:
        print("autotuning: no feasible config found")
        return 1
    import json

    print(json.dumps(best["config"], indent=2, default=str))
    return 0
