"""Autotuner.

Counterpart of the reference's ``Autotuner``
(``deepspeed/autotuning/autotuner.py:42``): profile the model, derive which
ZeRO stages fit memory (``get_instantiation_memory_required_per_gpu``
reference :278), generate a candidate-config grid, run short trials, pick
the best by throughput/latency (``autotuning_metric``).

TPU deltas: trials run in-process (one jit cache per trial; the reference
schedules separate jobs because CUDA state is poisoned per process — XLA
recompiles cleanly), and memory feasibility uses the analytic ZeRO
estimator plus the compiled step's own memory analysis when available.
"""

from __future__ import annotations

import itertools
import random as _random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.zero.partition import estimate_zero_memory
from deepspeed_tpu.utils.logging import logger

DEFAULT_MICRO_BATCHES = [1, 2, 4, 8, 16]
DEFAULT_STAGES = [0, 1, 2, 3]

AUTOTUNING_METRIC_THROUGHPUT = "throughput"
AUTOTUNING_METRIC_LATENCY = "latency"


class BaseTuner:
    """(reference autotuning/tuner/base_tuner.py)"""

    def __init__(self, exps: List[Dict]):
        self.all_exps = list(exps)

    def next_batch(self, sample_size: int) -> List[Dict]:
        raise NotImplementedError

    def has_next(self) -> bool:
        return bool(self.all_exps)


class GridSearchTuner(BaseTuner):
    """Exhaustive order (reference tuner/index_based_tuner.py)."""

    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class RandomTuner(BaseTuner):
    """Random order (reference tuner/index_based_tuner.py RandomTuner)."""

    def __init__(self, exps: List[Dict], seed: int = 0):
        super().__init__(exps)
        _random.Random(seed).shuffle(self.all_exps)

    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class ModelBasedTuner(BaseTuner):
    """Cost-model-guided order (reference tuner/model_based_tuner.py):
    candidates sorted by predicted per-chip memory headroom (larger micro
    batches first among feasible — the throughput prior)."""

    def __init__(self, exps: List[Dict], hbm_bytes: int, n_params: int, dp: int):
        def score(exp):
            zc = exp["zero_optimization"]["stage"]
            mem = estimate_zero_memory(n_params, zc, dp)["total_bytes"]
            headroom = hbm_bytes - mem
            return (headroom < 0, -exp["train_micro_batch_size_per_gpu"], zc)

        super().__init__(sorted(exps, key=score))

    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class Autotuner:
    def __init__(
        self,
        model_factory: Callable[[], Any],
        base_config: Dict,
        batch_factory: Callable[[int], Any],
        micro_batches: Optional[List[int]] = None,
        stages: Optional[List[int]] = None,
        metric: str = AUTOTUNING_METRIC_THROUGHPUT,
        tuner_type: str = "gridsearch",
        trial_steps: int = 5,
        warmup_steps: int = 2,
        max_trials: int = 50,
        hbm_bytes: int = 16 * 2**30,
    ):
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        self.batch_factory = batch_factory
        self.micro_batches = micro_batches or DEFAULT_MICRO_BATCHES
        self.stages = stages or DEFAULT_STAGES
        self.metric = metric
        self.tuner_type = tuner_type
        self.trial_steps = trial_steps
        self.warmup_steps = warmup_steps
        self.max_trials = max_trials
        self.hbm_bytes = hbm_bytes
        self.results: List[Dict] = []

    # --- model info (reference model_info_profile_run :663) ---------------
    def model_info(self) -> Dict[str, Any]:
        import jax

        model = self.model_factory()
        batch = self.batch_factory(1)
        shapes = jax.eval_shape(
            lambda r, b: model.init(r, b) if hasattr(model, "init") else model[0](r, b),
            jax.random.PRNGKey(0),
            batch,
        )
        n = sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes))
        return {"num_params": n}

    # --- candidate grid ---------------------------------------------------
    def generate_experiments(self) -> List[Dict]:
        """(stage, micro) sweep with the per-stage tuning templates applied
        (reference ``config_templates/``), memory-gated per candidate."""
        from deepspeed_tpu.autotuning.config_templates import candidate_configs

        info = self.model_info()
        n_params = info["num_params"]
        import jax

        dp = len(jax.devices())
        exps = []
        for cfg in candidate_configs(self.base_config, self.stages, self.micro_batches):
            stage = cfg["zero_optimization"]["stage"]
            mem = estimate_zero_memory(n_params, stage, dp)["total_bytes"]
            if mem > self.hbm_bytes:
                logger.debug(f"skip stage={stage} (needs {mem/2**30:.1f} GiB)")
                continue
            exps.append(cfg)
        return exps

    def _make_tuner(self, exps: List[Dict]) -> BaseTuner:
        if self.tuner_type == "random":
            return RandomTuner(exps)
        if self.tuner_type == "model_based":
            import jax

            info = self.model_info()
            return ModelBasedTuner(
                exps, self.hbm_bytes, info["num_params"], len(jax.devices())
            )
        return GridSearchTuner(exps)

    # --- trials -----------------------------------------------------------
    def run_trial(self, config: Dict) -> Optional[Dict]:
        import jax

        import deepspeed_tpu as ds
        import deepspeed_tpu.parallel.mesh as mesh_mod

        mesh_mod.reset_topology()
        micro = config["train_micro_batch_size_per_gpu"]
        try:
            engine, _, _, _ = ds.initialize(
                model=self.model_factory(), config=config, dist_init_required=False
            )
            batch = self.batch_factory(micro * engine.data_parallel_world_size())
            for _ in range(self.warmup_steps):
                loss = engine(batch)
                engine.backward(loss)
                engine.step()
            jax.device_get(loss)
            t0 = time.perf_counter()
            for _ in range(self.trial_steps):
                loss = engine(batch)
                engine.backward(loss)
                engine.step()
            jax.device_get(loss)
            dt = (time.perf_counter() - t0) / self.trial_steps
        except Exception as e:
            logger.warning(f"trial failed for {config.get('zero_optimization')}, mb={micro}: {e}")
            return None
        samples_per_sec = micro * engine.data_parallel_world_size() / dt
        return {
            "config": config,
            "latency_s": dt,
            "throughput_samples_per_s": samples_per_sec,
        }

    def tune(self) -> Optional[Dict]:
        from deepspeed_tpu.autotuning.scheduler import ResourceManager

        exps = self.generate_experiments()
        logger.info(f"autotuning over {len(exps)} candidate configs")
        tuner = self._make_tuner(exps)
        # the scheduler owns execution/status; the tuner owns the visit order
        self.scheduler = ResourceManager(self.run_trial, num_slots=1)
        trials = 0
        while tuner.has_next() and trials < self.max_trials:
            batch = tuner.next_batch(1)
            self.scheduler.schedule_all(batch)
            trials += len(batch)
        for exp in self.scheduler.run():
            if exp.result is not None:
                self.results.append(exp.result)
        if not self.results:
            return None
        if self.metric == AUTOTUNING_METRIC_LATENCY:
            best = min(self.results, key=lambda r: r["latency_s"])
        else:
            best = max(self.results, key=lambda r: r["throughput_samples_per_s"])
        logger.info(
            f"autotuning best: stage={best['config']['zero_optimization']['stage']} "
            f"micro={best['config']['train_micro_batch_size_per_gpu']} "
            f"({best['throughput_samples_per_s']:.1f} samples/s)"
        )
        return best


def run_autotuning(args) -> int:
    """CLI entry (reference runner.py:360): the user script is expected to
    define ``model_factory``/``batch_factory``/``base_config``; exec it and
    tune."""
    namespace: Dict[str, Any] = {}
    with open(args.user_script) as f:
        code = f.read()
    exec(compile(code, args.user_script, "exec"), namespace)  # noqa: S102
    required = ("model_factory", "batch_factory", "base_config")
    if not all(k in namespace for k in required):
        raise RuntimeError(
            f"--autotuning requires the script to define {required} "
            "(see deepspeed_tpu.autotuning.Autotuner)"
        )
    tuner = Autotuner(
        namespace["model_factory"], namespace["base_config"], namespace["batch_factory"]
    )
    best = tuner.tune()
    if best is None:
        print("autotuning: no feasible config found")
        return 1
    import json

    print(json.dumps(best["config"], indent=2, default=str))
    return 0
