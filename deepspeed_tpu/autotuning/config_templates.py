"""Per-stage tuning templates (reference:
``deepspeed/autotuning/config_templates/template_zero{0..3}.json``).

Each template is the set of stage-specific knobs worth sweeping; the
autotuner overlays them onto the user's base config when generating
candidates. Values are TPU-adjusted: bucket sizes steer XLA's collective
combining rather than NCCL chunking, and ``overlap_comm`` maps onto the
latency-hiding scheduler (always profitable, so stage-3 sweeps it on)."""

from __future__ import annotations

import copy
from typing import Dict, List

TEMPLATE_ZERO0: Dict = {"zero_optimization": {"stage": 0}}

TEMPLATE_ZERO1: Dict = {
    "zero_optimization": {
        "stage": 1,
        "reduce_bucket_size": int(5e8),
        "allgather_bucket_size": int(5e8),
    }
}

TEMPLATE_ZERO2: Dict = {
    "zero_optimization": {
        "stage": 2,
        "allgather_partitions": True,
        "allgather_bucket_size": int(5e8),
        "overlap_comm": False,
        "reduce_scatter": True,
        "reduce_bucket_size": int(5e8),
        "contiguous_gradients": False,
    }
}

TEMPLATE_ZERO3: Dict = {
    "zero_optimization": {
        "stage": 3,
        "overlap_comm": True,
        "reduce_bucket_size": int(5e8),
        "stage3_prefetch_bucket_size": int(5e7),
        "stage3_param_persistence_threshold": int(1e5),
        "stage3_max_live_parameters": int(1e9),
        "stage3_max_reuse_distance": int(1e9),
    }
}

STAGE_TEMPLATES: Dict[int, Dict] = {
    0: TEMPLATE_ZERO0,
    1: TEMPLATE_ZERO1,
    2: TEMPLATE_ZERO2,
    3: TEMPLATE_ZERO3,
}


def template_for_stage(stage: int) -> Dict:
    if stage not in STAGE_TEMPLATES:
        raise ValueError(f"no tuning template for zero stage {stage}")
    return copy.deepcopy(STAGE_TEMPLATES[stage])


def merge_config(base: Dict, overlay: Dict) -> Dict:
    """Recursive dict merge, overlay wins; user-set keys in ``base`` win over
    template defaults (the reference keeps user values)."""
    out = copy.deepcopy(overlay)
    for k, v in base.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_config(v, out[k])
        else:
            out[k] = copy.deepcopy(v)
    return out


def candidate_configs(base: Dict, stages: List[int], micro_batches: List[int]) -> List[Dict]:
    """The (stage, micro-batch) sweep with stage templates applied."""
    out = []
    for stage in stages:
        tpl = template_for_stage(stage)
        for micro in micro_batches:
            cfg = merge_config(base, tpl)
            # the sweep owns the stage and micro-batch choices
            cfg["zero_optimization"]["stage"] = stage
            cfg["train_micro_batch_size_per_gpu"] = micro
            cfg.pop("train_batch_size", None)
            out.append(cfg)
    return out
