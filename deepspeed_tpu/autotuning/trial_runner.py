"""One autotuning trial in an isolated process.

Counterpart of the reference's per-experiment launch
(``deepspeed/autotuning/scheduler.py`` ``run_job`` — each experiment runs as
its own ``deepspeed`` launch with DS_AUTOTUNING env and a result file). On
one TPU host the isolation is a subprocess: a trial that OOMs HBM or takes
the XLA runtime down kills only itself, the sweep continues, and the parent
enforces a hard timeout (the tunneled backend can stall indefinitely).

Usage (spawned by ``scheduler.SubprocessTrialRunner``)::

    python -m deepspeed_tpu.autotuning.trial_runner \
        --script user_tuning.py --config exp.json --out result.json

``--script`` must define ``model_factory``, ``batch_factory`` and
``base_config`` (the same contract as ``deepspeed --autotuning``).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--script", required=True)
    p.add_argument("--config", required=True, help="path to the trial config json")
    p.add_argument("--out", required=True, help="path to write the result json")
    p.add_argument("--trial-steps", type=int, default=5)
    p.add_argument("--warmup-steps", type=int, default=2)
    args = p.parse_args(argv)

    from deepspeed_tpu.autotuning.autotuner import Autotuner, load_user_script

    namespace = load_user_script(args.script)
    with open(args.config) as f:
        config = json.load(f)

    tuner = Autotuner(
        namespace["model_factory"],
        namespace["base_config"],
        namespace["batch_factory"],
        trial_steps=args.trial_steps,
        warmup_steps=args.warmup_steps,
    )
    result = tuner.run_trial(config)
    if result is None:
        return 1
    with open(args.out, "w") as f:
        json.dump(result, f, default=str)
    return 0


if __name__ == "__main__":
    sys.exit(main())
