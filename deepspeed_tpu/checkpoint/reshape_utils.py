"""Checkpoint reshaping utilities.

Counterpart of the reference's ``deepspeed/checkpoint/reshape_meg_2d.py`` /
``reshape_3d_utils.py``: re-slice tensor-parallel checkpoint shards to a new
TP degree and re-group (tp, pp, dp) file layouts. On TPU most resharding is
free (orbax stores GLOBAL arrays; loading under a different mesh re-shards
automatically) — these utilities exist for importing/exporting checkpoints
that arrive as per-rank shard files (Megatron-style)."""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def merge_tp_slices(slices: Sequence[np.ndarray], axis: int) -> np.ndarray:
    """Concatenate one param's TP shards back to the full tensor."""
    return np.concatenate([np.asarray(s) for s in slices], axis=axis)


def split_tp_slices(full: np.ndarray, degree: int, axis: int) -> List[np.ndarray]:
    """Slice a full tensor into ``degree`` TP shards along ``axis``."""
    if full.shape[axis] % degree != 0:
        raise ValueError(
            f"dim {full.shape[axis]} not divisible by target tp degree {degree}"
        )
    return [np.ascontiguousarray(s) for s in np.split(full, degree, axis=axis)]


def reshape_tp_degree(
    shards: Sequence[np.ndarray], old_degree: int, new_degree: int, axis: int
) -> List[np.ndarray]:
    """old-degree shards → new-degree shards (reference reshape_meg_2d)."""
    assert len(shards) == old_degree
    return split_tp_slices(merge_tp_slices(shards, axis), new_degree, axis)


class ReshapeMeg2D:
    """Grid bookkeeping for (tp, pp) rank files (reference
    ``meg_2d_parallel_map``)."""

    def __init__(self, old_tp: int, old_pp: int, new_tp: int, new_pp: int):
        self.old_tp, self.old_pp = old_tp, old_pp
        self.new_tp, self.new_pp = new_tp, new_pp
        if old_pp != new_pp:
            raise NotImplementedError(
                "pp-degree reshaping requires layer re-partitioning; reshape tp first"
            )

    def old_rank(self, tp: int, pp: int) -> int:
        return pp * self.old_tp + tp

    def new_rank(self, tp: int, pp: int) -> int:
        return pp * self.new_tp + tp

    def source_ranks_for(self, new_tp_rank: int, pp: int) -> List[int]:
        """Which old tp ranks contribute to one new tp rank."""
        if self.new_tp <= self.old_tp:
            ratio = self.old_tp // self.new_tp
            return [self.old_rank(new_tp_rank * ratio + i, pp) for i in range(ratio)]
        ratio = self.new_tp // self.old_tp
        return [self.old_rank(new_tp_rank // ratio, pp)]


def partition_data(world: int, num_items: int) -> List[List[int]]:
    """Contiguous dp partition of item indices (reference reshape_3d dp_map)."""
    per = (num_items + world - 1) // world
    return [list(range(r * per, min(num_items, (r + 1) * per))) for r in range(world)]
