"""Export training state in the reference's checkpoint layout.

The inverse of ``reference_ingest.py``: write the torch-pickle file family
the reference emits for a ZeRO stage-1/2 run (``deepspeed/runtime/engine.py:
2588`` ``_save_checkpoint`` + ``:2961`` ``_save_zero_checkpoint``), so a
deepspeed_tpu run can round-trip back into the reference ecosystem — its
``zero_to_fp32.py`` consolidation script consumes exactly these files:

* ``{tag}/mp_rank_00_model_states.pt`` — ``module`` weights (compute
  dtype), plus the bookkeeping zero_to_fp32 requires: ``param_shapes``
  (per-group name → ``torch.Size``), ``buffer_names``, ``shared_params``,
  ``ds_version``, ``iteration``;
* ``{tag}/zero_pp_rank_{dp}_mp_rank_00_optim_states.pt`` — each dp rank's
  flat fp32 master partition under ``optimizer_state_dict`` with
  ``zero_stage`` / ``partition_count`` / ``single_partition_of_fp32_groups``
  (the keys ``zero_to_fp32.py:parse_optim_states`` reads);
* ``latest`` — the tag pointer.

Tensor names are the TPU model's flat tree paths (stacked ``layers/...``
arrays stay stacked): both the reference consolidation script and our own
``reference_ingest`` treat names as opaque strings, so the round-trip is
exact. TP export is always mp_rank_00 — global arrays are already merged;
a reference run wanting TP shards re-shards at load time.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Optional

import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def export_reference_checkpoint(
    engine,
    save_dir: str,
    tag: Optional[str] = None,
    dp_shards: Optional[int] = None,
) -> str:
    """Write ``engine``'s weights + fp32 masters in the reference layout.

    ``dp_shards`` controls how many ``zero_pp_rank_*`` files the flat fp32
    masters are split across (default: the engine's data-parallel world
    size, matching what a same-size reference run would have written).
    Returns the tag directory path.
    """
    import torch

    from deepspeed_tpu import comm as dist
    from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths

    if not getattr(engine, "_initialized", False):
        raise RuntimeError("cannot export before the engine state is initialized")
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    if dp_shards is None:
        dp_shards = max(int(engine.data_parallel_world_size()), 1)

    import jax

    # Consolidation runs on EVERY process (device_get of dp-sharded global
    # arrays needs all participants, like save_16bit_model); only the file
    # writes below are rank-gated.
    masters = {
        name: np.asarray(jax.device_get(leaf), np.float32)
        for name, leaf in _flatten_with_paths(engine.get_master_params()).items()
    }
    module_sd = engine.consolidated_16bit_state_dict()

    path = os.path.join(save_dir, tag)
    if dist.get_rank() != 0:
        # writes are rank-0-only; every rank leaves together so no caller
        # reads the tag dir before it is complete
        dist.barrier(name="export_reference_checkpoint")
        return path
    os.makedirs(path, exist_ok=True)

    names = list(masters.keys())  # _flatten_with_paths order (sorted paths)
    param_shapes = OrderedDict(
        (name, torch.Size(masters[name].shape)) for name in names
    )
    flat = np.concatenate([masters[n].ravel() for n in names]) if names else np.zeros(0, np.float32)
    pad = (-flat.size) % dp_shards
    flat = np.pad(flat, (0, pad))  # dp-divisibility padding, like the
    # reference's flat-buffer alignment; consolidation ignores the tail
    partitions = np.split(flat, dp_shards)

    def _to_torch(v: np.ndarray) -> "torch.Tensor":
        """Preserve the compute dtype (the reference's model_states carry
        bf16/fp16 weights); numpy's extension bf16 routes through fp32."""
        if v.dtype.name == "bfloat16":
            return torch.from_numpy(
                np.ascontiguousarray(v.astype(np.float32))
            ).to(torch.bfloat16)
        return torch.from_numpy(np.ascontiguousarray(v))

    zero_stage = min(int(getattr(engine, "zero_optimization_stage", lambda: 1)()), 2)
    model_state = {
        "module": {k: _to_torch(np.asarray(v)) for k, v in module_sd.items()},
        "buffer_names": [],
        "shared_params": {},
        "param_shapes": [param_shapes],
        "dp_world_size": dp_shards,
        "mp_world_size": 1,
        "iteration": int(engine.global_steps),
        "global_steps": int(engine.global_steps),
        "ds_version": "0.10.2+tpu",
    }
    from deepspeed_tpu.checkpoint.utils import (
        get_model_ckpt_name_for_rank,
        get_zero_ckpt_name_for_rank,
    )

    torch.save(model_state, get_model_ckpt_name_for_rank(path, "00"))

    for dp, part in enumerate(partitions):
        optim_state = {
            "optimizer_state_dict": {
                "zero_stage": zero_stage,
                "partition_count": dp_shards,
                "single_partition_of_fp32_groups": [
                    torch.from_numpy(np.ascontiguousarray(part))
                ],
                "ds_version": "0.10.2+tpu",
            }
        }
        torch.save(
            optim_state,
            get_zero_ckpt_name_for_rank(path, dp, 0),
        )

    from deepspeed_tpu.runtime.checkpoint_engine.atomic import write_latest_marker

    write_latest_marker(save_dir, tag)
    log_dist(
        f"exported reference-layout checkpoint: {path} "
        f"({len(names)} tensors, dp_shards={dp_shards})",
        ranks=[0],
    )
    dist.barrier(name="export_reference_checkpoint")  # pairs with the
    # non-rank-0 barrier above: all ranks leave after the files exist
    return path
