"""General 3D checkpoint re-layout: (tp, pp, dp) → (tp', pp', dp').

Counterpart of the reference's ``deepspeed/checkpoint/reshape_meg_2d.py``
(``reshape_meg_2d_parallel``), ``reshape_3d_utils.py`` (``model_3d_desc``)
and ``zero_checkpoint.py`` (``ZeROCheckpoint`` merge): re-laying a
Megatron-family checkpoint — per-layer ``layer_XX-model_YY-model_states.pt``
files, per-(pp,tp)-rank ``mp_rank_XX_model_states.pt`` files and per-dp-rank
``zero_pp_rank_D_mp_rank_XX_optim_states.pt`` ZeRO shards — onto a different
parallel topology.

Strategy differs from the reference deliberately. The reference remaps and
merges FILES, so it can only contract (new degree ≤ old, divisibility
required). Here every re-layout goes through a CANONICAL full-tensor form
(layer → {param → full array}, plus full fp32 masters and Adam moments) and
re-emits the target file family from it — the on-disk analogue of GSPMD's
global-array resharding, correct for arbitrary targets including expansion.

TP split/merge axes come from the model's ``tp_partition_rules`` — the same
specs that drive GSPMD shardings drive checkpoint slicing — recorded in the
``mp_rank`` files under ``tp_axes`` on export and recovered from there on
read (falling back to reference-style name heuristics for foreign files).
"""

from __future__ import annotations

import glob
import os
import re
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.atomic import write_latest_marker

from deepspeed_tpu.checkpoint.reference_ingest import (
    _resolve_tag_dir,
    _to_numpy,
    _torch_load,
)
from deepspeed_tpu.checkpoint.reshape_utils import (
    merge_tp_slices,
    partition_data,
    split_tp_slices,
)
from deepspeed_tpu.checkpoint.utils import (
    get_model_ckpt_name_for_rank,
    get_zero_ckpt_name_for_rank,
)
from deepspeed_tpu.utils.logging import log_dist


def _key_order(key: str) -> int:
    """Layer keys sort NUMERICALLY ('02' < '10' < '100'); a string sort
    would permute stacks past 99 layers. SHARED_KEY ('00') stays first."""
    return int(key)

LAYER_RE = re.compile(r"layer_(\d+)-model_(\d+)-model_states\.pt$")
MP_RE = re.compile(r"mp_rank_(\d+)_model_states\.pt$")
ZERO_RE = re.compile(r"(?:bf16_)?zero_pp_rank_(\d+)_mp_rank_(\d+)_optim_states\.pt$")

# Foreign (no recorded tp_axes) checkpoints: reference
# deepspeed_checkpoint.py LAYER_CONCAT_DIM — row-parallel weights merge on
# the input-features axis; everything else defaults to axis 0 unless the
# shards are identical (replicated).
_ROW_PARALLEL_HINTS = ("wo", "w_out", "self_attention.dense.weight", "mlp.dense_4h_to_h.weight")

LAYERS_PREFIX = "layers/"
SHARED_KEY = "00"  # non-layer params (embeddings, final norm, head) live here


class Model3DDescriptor:
    """(tp, pp, dp) of a checkpoint directory (reference ``model_3d_desc``)."""

    def __init__(self, tp_degree: int = 1, pp_degree: int = 1, dp_degree: int = 1):
        self.tp_degree = int(tp_degree)
        self.pp_degree = int(pp_degree)
        self.dp_degree = int(dp_degree)

    def world_size(self) -> int:
        return self.tp_degree * self.pp_degree * self.dp_degree

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Model3DDescriptor)
            and (self.tp_degree, self.pp_degree, self.dp_degree)
            == (other.tp_degree, other.pp_degree, other.dp_degree)
        )

    def __repr__(self) -> str:
        return f"Model3DDescriptor(tp={self.tp_degree}, pp={self.pp_degree}, dp={self.dp_degree})"


def describe_checkpoint(path: str) -> Model3DDescriptor:
    """Infer (tp, pp, dp) from the file family (reference
    ``get_model_3d_descriptor``)."""
    mp_files = [f for f in os.listdir(path) if MP_RE.search(f)]
    layer_files = [f for f in os.listdir(path) if LAYER_RE.search(f)]
    zero_files = [f for f in os.listdir(path) if ZERO_RE.search(f)]
    if layer_files:
        first_key = sorted(LAYER_RE.search(f).group(1) for f in layer_files)[0]
        tp = len([f for f in layer_files if LAYER_RE.search(f).group(1) == first_key])
        pp = max(1, len(mp_files) // tp)
    else:
        tp = max(1, len(mp_files))
        pp = 1
    dp = max(1, len(zero_files) // max(1, tp * pp)) if zero_files else 1
    return Model3DDescriptor(tp_degree=tp, pp_degree=pp, dp_degree=dp)


# --------------------------------------------------------------------------
# engine state → canonical form


def _spec_axis(spec) -> Optional[int]:
    """Index of the 'model' mesh axis in a PartitionSpec (None: replicated)."""
    if spec is None:
        return None
    for i, part in enumerate(tuple(spec)):
        names = part if isinstance(part, (tuple, list)) else (part,)
        if "model" in [n for n in names if n is not None]:
            return i
    return None


def engine_canonical_state(engine) -> Dict[str, Any]:
    """Read ``engine``'s training state into the canonical full-tensor form:
    ``layers[key][name]`` (module dtype), ``fp32``/``exp_avg``/``exp_avg_sq``
    parallel structures, per-param TP axes, and run counters."""
    import jax

    from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths

    if not getattr(engine, "_initialized", False):
        raise RuntimeError("cannot export before the engine state is initialized")

    master_flat = {
        name: np.asarray(jax.device_get(v), np.float32)
        for name, v in _flatten_with_paths(engine.get_master_params()).items()
    }
    module_flat = {
        name: np.asarray(v) for name, v in engine.consolidated_16bit_state_dict().items()
    }

    opt = engine._opt_state
    moments: Dict[str, Dict[str, np.ndarray]] = {}
    opt_step = 0
    if opt is not None and hasattr(opt, "exp_avg") and hasattr(opt, "exp_avg_sq"):
        for kind in ("exp_avg", "exp_avg_sq"):
            moments[kind] = {
                name: np.asarray(jax.device_get(v), np.float32)
                for name, v in _flatten_with_paths(getattr(opt, kind)).items()
            }
        opt_step = int(jax.device_get(opt.step)) if hasattr(opt, "step") else 0

    specs = engine.module.tp_partition_rules(engine.get_master_params())
    spec_flat = _flatten_with_paths(specs) if specs is not None else {}

    def split_layers(flat: Dict[str, np.ndarray], stacked_axis_shift: bool):
        layers: "OrderedDict[str, OrderedDict[str, np.ndarray]]" = OrderedDict()
        layers[SHARED_KEY] = OrderedDict()
        n_layers = 0
        for name, arr in flat.items():
            if name.startswith(LAYERS_PREFIX):
                n_layers = max(n_layers, arr.shape[0])
        for i in range(n_layers):
            layers[f"{i + 1:02d}"] = OrderedDict()
        for name, arr in flat.items():
            if name.startswith(LAYERS_PREFIX):
                sub = name[len(LAYERS_PREFIX):]
                for i in range(arr.shape[0]):
                    layers[f"{i + 1:02d}"][sub] = np.ascontiguousarray(arr[i])
            else:
                layers[SHARED_KEY][name] = arr
        return layers

    canon = {
        "layers": split_layers(module_flat, True),
        "fp32": split_layers(master_flat, True),
        "global": {
            "iteration": int(engine.global_steps),
            "global_samples": int(engine.global_samples),
            "micro_steps": int(engine.micro_steps),
            "skipped_steps": int(engine.skipped_steps),
            "opt_step": opt_step,
            "lr_scheduler": engine.lr_scheduler.state_dict()
            if engine.lr_scheduler is not None
            else None,
            "ds_version": "0.10.2+tpu",
        },
    }
    for kind in ("exp_avg", "exp_avg_sq"):
        canon[kind] = split_layers(moments[kind], True) if kind in moments else None

    # per-(layer, name) TP split axis, in PER-LAYER coordinates (the stacked
    # [L, ...] leading dim is dropped for layer params)
    tp_axes: Dict[str, Dict[str, Optional[int]]] = {}
    for key, group in canon["layers"].items():
        tp_axes[key] = {}
        for name in group:
            full_name = name if key == SHARED_KEY else LAYERS_PREFIX + name
            axis = _spec_axis(spec_flat.get(full_name))
            if axis is not None and key != SHARED_KEY:
                axis -= 1  # un-stack: spec axis 0 is the scanned layer dim
            tp_axes[key][name] = axis
    canon["tp_axes"] = tp_axes
    return canon


# --------------------------------------------------------------------------
# canonical form → reference file family


def _shard(arr: np.ndarray, axis: Optional[int], tp: int, t: int) -> np.ndarray:
    if axis is None or tp == 1:
        return arr
    return split_tp_slices(arr, tp, axis)[t]


def write_reference_layout(
    canon: Dict[str, Any], path: str, tp: int = 1, pp: int = 1, dp: int = 1
) -> str:
    """Emit the canonical state as the reference's Megatron file family."""
    import torch

    os.makedirs(path, exist_ok=True)
    layer_keys = list(canon["layers"].keys())
    # Effective axes FOR THIS tp degree: a dim not divisible by tp stays
    # replicated, and the recorded metadata must say so — the reader merging
    # on a nominal axis would concatenate identical replicas. The NOMINAL
    # axes are recorded alongside so a later reshape to a compatible tp can
    # still slice (a tp=1 layout would otherwise erase every axis).
    nominal_axes = canon["tp_axes"]
    tp_axes = {
        key: {
            name: (
                axis
                if axis is not None
                and tp > 1
                and canon["layers"][key][name].shape[axis] % tp == 0
                else None
            )
            for name, axis in nominal_axes[key].items()
        }
        for key in nominal_axes
    }

    def to_torch(v: np.ndarray):
        if v.dtype.name == "bfloat16":
            return torch.from_numpy(np.ascontiguousarray(v.astype(np.float32))).to(torch.bfloat16)
        return torch.from_numpy(np.ascontiguousarray(v))

    for key in layer_keys:
        for t in range(tp):
            sd = {
                name: to_torch(_shard(arr, tp_axes[key].get(name), tp, t))
                for name, arr in canon["layers"][key].items()
            }
            torch.save(sd, os.path.join(path, f"layer_{key}-model_{t:02d}-model_states.pt"))

    stage_keys = [
        [layer_keys[i] for i in idxs] for idxs in partition_data(pp, len(layer_keys))
    ]
    has_zero = canon.get("fp32") is not None

    for p in range(pp):
        for t in range(tp):
            rank = p * tp + t
            # flat-group order for this rank's ZeRO shards: layer key order,
            # then insertion (name) order — recorded in param_shapes so the
            # reader re-splits without guessing
            shapes: "OrderedDict[str, Any]" = OrderedDict()
            fp32_parts, m_parts, v_parts = [], [], []
            for key in stage_keys[p]:
                for name, arr in canon["layers"][key].items():
                    axis = tp_axes[key].get(name)
                    shard_shape = _shard(arr, axis, tp, t).shape
                    shapes[f"{key}:{name}"] = torch.Size(shard_shape)
                    if has_zero:
                        fp32_parts.append(
                            _shard(canon["fp32"][key][name], axis, tp, t).ravel()
                        )
                        if canon.get("exp_avg") is not None:
                            m_parts.append(
                                _shard(canon["exp_avg"][key][name], axis, tp, t).ravel()
                            )
                            v_parts.append(
                                _shard(canon["exp_avg_sq"][key][name], axis, tp, t).ravel()
                            )
            torch.save(
                {
                    "iteration": canon["global"].get("iteration", 0),
                    "global_steps": canon["global"].get("iteration", 0),
                    "args": None,
                    "ds_version": canon["global"].get("ds_version", "0.10.2+tpu"),
                    "tp_degree": tp,
                    "pp_degree": pp,
                    "dp_degree": dp,
                    "pp_layer_keys": stage_keys[p],
                    "tp_axes": nominal_axes,
                    "tp_axes_effective": tp_axes,
                    "param_shapes": [shapes],
                    "global_state": canon["global"],
                },
                get_model_ckpt_name_for_rank(path, f"{rank:02d}"),
            )
            if not has_zero:
                continue

            def dp_split(parts: List[np.ndarray]) -> List[np.ndarray]:
                flat = (
                    np.concatenate(parts).astype(np.float32)
                    if parts
                    else np.zeros(0, np.float32)
                )
                flat = np.pad(flat, (0, (-flat.size) % dp))
                return np.split(flat, dp)

            fp32_dp = dp_split(fp32_parts)
            m_dp = dp_split(m_parts) if m_parts else None
            v_dp = dp_split(v_parts) if v_parts else None
            for d in range(dp):
                osd: Dict[str, Any] = {
                    "zero_stage": 1,
                    "partition_count": dp,
                    "single_partition_of_fp32_groups": [to_torch(fp32_dp[d])],
                    "ds_version": canon["global"].get("ds_version", "0.10.2+tpu"),
                }
                if m_dp is not None:
                    osd["base_optimizer_state"] = {
                        "state": [
                            {
                                "step": canon["global"].get("opt_step", 0),
                                "exp_avg": to_torch(m_dp[d]),
                                "exp_avg_sq": to_torch(v_dp[d]),
                            }
                        ]
                    }
                torch.save(
                    {"optimizer_state_dict": osd},
                    get_zero_ckpt_name_for_rank(path, d, rank),
                )
    return path


# --------------------------------------------------------------------------
# reference file family → canonical form


def _heuristic_axis(name: str, shards: List[np.ndarray]) -> Optional[int]:
    if len(shards) == 1 or shards[0].ndim == 0:
        return None
    if all(s.shape == shards[0].shape and np.array_equal(s, shards[0]) for s in shards[1:]):
        return None  # replicated
    short = name.split("/")[-1]
    if short in _ROW_PARALLEL_HINTS or any(name.endswith(h) for h in _ROW_PARALLEL_HINTS):
        return min(1, shards[0].ndim - 1)
    return 0


def read_reference_layout(path: str) -> Dict[str, Any]:
    """Read a Megatron-family checkpoint directory into canonical form."""

    def load(p):
        return _torch_load(p)

    def to_np(t) -> np.ndarray:
        return _to_numpy(t, preserve_bf16=True)

    desc = describe_checkpoint(path)
    tp, pp, dp = desc.tp_degree, desc.pp_degree, desc.dp_degree
    mp_files = sorted(
        (f for f in os.listdir(path) if MP_RE.search(f)),
        key=lambda f: int(MP_RE.search(f).group(1)),
    )
    if not mp_files:
        raise FileNotFoundError(f"no mp_rank_*_model_states.pt under {path}")
    mp0 = load(os.path.join(path, mp_files[0]))
    # nominal axes survive re-splitting at any target tp; the EFFECTIVE axes
    # are what this layout actually sliced with (non-divisible dims stay
    # replicated) and are what merging must use
    nominal_axes = mp0.get("tp_axes")
    merge_axes = mp0.get("tp_axes_effective") or nominal_axes
    if "tp_degree" in mp0:
        tp, pp, dp = int(mp0["tp_degree"]), int(mp0["pp_degree"]), int(mp0["dp_degree"])

    # ---- layer files → full tensors --------------------------------------
    layer_files = [f for f in os.listdir(path) if LAYER_RE.search(f)]
    layers: "OrderedDict[str, OrderedDict[str, np.ndarray]]" = OrderedDict()
    tp_axes: Dict[str, Dict[str, Optional[int]]] = {}
    eff_axes: Dict[str, Dict[str, Optional[int]]] = {}
    if layer_files:
        keys = sorted({LAYER_RE.search(f).group(1) for f in layer_files}, key=_key_order)
        for key in keys:
            per_tp = []
            for t in range(tp):
                f = os.path.join(path, f"layer_{key}-model_{t:02d}-model_states.pt")
                per_tp.append({k: to_np(v) for k, v in load(f).items()})
            layers[key] = OrderedDict()
            tp_axes[key] = {}
            eff_axes[key] = {}
            for name in per_tp[0]:
                shards = [m[name] for m in per_tp]
                if merge_axes is not None:
                    axis = merge_axes.get(key, {}).get(name)
                else:
                    axis = _heuristic_axis(name, shards)
                eff_axes[key][name] = axis
                tp_axes[key][name] = (
                    nominal_axes.get(key, {}).get(name) if nominal_axes is not None else axis
                )
                layers[key][name] = (
                    shards[0] if axis is None else merge_tp_slices(shards, axis)
                )
    else:
        # flat (non-pipeline) checkpoints: whole module as the shared layer
        per_tp = [load(os.path.join(path, f)) for f in mp_files]
        modules = [{k: to_np(v) for k, v in (s.get("module") or {}).items()} for s in per_tp]
        layers[SHARED_KEY] = OrderedDict()
        tp_axes[SHARED_KEY] = {}
        eff_axes[SHARED_KEY] = {}
        for name in modules[0]:
            shards = [m[name] for m in modules]
            if merge_axes is not None:
                axis = merge_axes.get(SHARED_KEY, {}).get(name)
            else:
                axis = _heuristic_axis(name, shards)
            eff_axes[SHARED_KEY][name] = axis
            tp_axes[SHARED_KEY][name] = (
                nominal_axes.get(SHARED_KEY, {}).get(name)
                if nominal_axes is not None
                else axis
            )
            layers[SHARED_KEY][name] = (
                shards[0] if axis is None else merge_tp_slices(shards, axis)
            )

    canon: Dict[str, Any] = {
        "layers": layers,
        "tp_axes": tp_axes,
        "fp32": None,
        "exp_avg": None,
        "exp_avg_sq": None,
        "global": dict(
            mp0.get("global_state")
            or {"iteration": int(mp0.get("iteration") or mp0.get("global_steps") or 0)}
        ),
    }

    # ---- zero shards → full fp32/moments ---------------------------------
    zero_any = [f for f in os.listdir(path) if ZERO_RE.search(f)]
    if not zero_any:
        return canon
    fp32: "OrderedDict[str, OrderedDict[str, np.ndarray]]" = OrderedDict(
        (k, OrderedDict()) for k in layers
    )
    exp_avg = OrderedDict((k, OrderedDict()) for k in layers)
    exp_avg_sq = OrderedDict((k, OrderedDict()) for k in layers)
    have_moments = False
    # shard slices per (key, name): one entry per contributing tp rank
    slices: Dict[Any, Dict[int, Dict[str, np.ndarray]]] = {}
    for rank_file in mp_files:
        rank = int(MP_RE.search(rank_file).group(1))
        sd = load(os.path.join(path, rank_file))
        shapes_groups = sd.get("param_shapes")
        if shapes_groups is None:
            raise ValueError(f"{rank_file} records no param_shapes; cannot split ZeRO shards")
        t = rank % tp
        zfiles = sorted(
            glob.glob(os.path.join(path, f"*zero_pp_rank_*_mp_rank_{rank:02d}_optim_states.pt")),
            key=lambda p: int(ZERO_RE.search(p).group(1)),
        )
        zstates = [load(f)["optimizer_state_dict"] for f in zfiles]
        for g, shapes in enumerate(shapes_groups):
            flat = np.concatenate(
                [to_np(z["single_partition_of_fp32_groups"][g]).ravel() for z in zstates]
            )
            flat_m = flat_v = None
            if zstates and "base_optimizer_state" in zstates[0]:
                have_moments = True
                flat_m = np.concatenate(
                    [to_np(z["base_optimizer_state"]["state"][g]["exp_avg"]).ravel() for z in zstates]
                )
                flat_v = np.concatenate(
                    [
                        to_np(z["base_optimizer_state"]["state"][g]["exp_avg_sq"]).ravel()
                        for z in zstates
                    ]
                )
            offset = 0
            for qualified, shape in shapes.items():
                key, name = qualified.split(":", 1) if ":" in qualified else (SHARED_KEY, qualified)
                n = int(np.prod(shape)) if len(shape) else 1
                rec = slices.setdefault((key, name), {})
                entry = {"fp32": flat[offset : offset + n].reshape(tuple(shape))}
                if flat_m is not None:
                    entry["exp_avg"] = flat_m[offset : offset + n].reshape(tuple(shape))
                    entry["exp_avg_sq"] = flat_v[offset : offset + n].reshape(tuple(shape))
                rec[t] = entry
                offset += n
    for (key, name), per_tp_slices in slices.items():
        axis = eff_axes.get(key, {}).get(name)
        ordered = [per_tp_slices[t] for t in sorted(per_tp_slices)]
        for kind, target in (("fp32", fp32), ("exp_avg", exp_avg), ("exp_avg_sq", exp_avg_sq)):
            if kind not in ordered[0]:
                continue
            shards = [o[kind] for o in ordered]
            target.setdefault(key, OrderedDict())[name] = (
                shards[0] if axis is None or len(shards) == 1 else merge_tp_slices(shards, axis)
            )
    canon["fp32"] = fp32
    if have_moments:
        canon["exp_avg"] = exp_avg
        canon["exp_avg_sq"] = exp_avg_sq
    return canon


# --------------------------------------------------------------------------
# public entry points


def export_megatron_checkpoint(
    engine, save_dir: str, tp: int = 1, pp: int = 1, dp: Optional[int] = None, tag: Optional[str] = None
) -> str:
    """Write ``engine``'s state as a reference Megatron-family checkpoint at
    the requested (tp, pp, dp) layout. Returns the tag directory."""
    from deepspeed_tpu import comm as dist

    if tag is None:
        tag = f"global_step{engine.global_steps}"
    if dp is None:
        dp = max(1, int(engine.data_parallel_world_size()))
    # canonical consolidation runs on EVERY process (device_get of
    # dp-sharded global arrays needs all participants); file writes are
    # rank-0-gated with a closing barrier, like reference_export.py:76
    canon = engine_canonical_state(engine)
    path = os.path.join(save_dir, tag)
    if dist.get_rank() == 0:
        write_reference_layout(canon, path, tp=tp, pp=pp, dp=dp)
        write_latest_marker(save_dir, tag)
    dist.barrier(name="export_megatron_checkpoint")
    log_dist(f"exported megatron-layout checkpoint: {path} (tp={tp} pp={pp} dp={dp})", ranks=[0])
    return path


def reshape_checkpoint_3d(
    src_dir: str,
    dst_dir: str,
    tp: int = 1,
    pp: int = 1,
    dp: int = 1,
    tag: Optional[str] = None,
) -> str:
    """Re-layout ``src_dir`` (a tag dir, or a dir with a ``latest`` pointer)
    onto (tp, pp, dp), writing the same file family under ``dst_dir``."""
    path = _resolve_tag_dir(src_dir, tag)
    if path != src_dir and tag is None:
        tag = os.path.basename(path)
    src_desc = describe_checkpoint(path)
    canon = read_reference_layout(path)
    out = dst_dir if tag is None else os.path.join(dst_dir, tag)
    write_reference_layout(canon, out, tp=tp, pp=pp, dp=dp)
    if tag is not None:
        write_latest_marker(dst_dir, tag)
    log_dist(
        f"reshaped checkpoint {src_desc} -> {Model3DDescriptor(tp, pp, dp)}: {out}",
        ranks=[0],
    )
    return out


def load_megatron_checkpoint(
    engine, load_dir: str, tag: Optional[str] = None, load_optimizer_states: bool = True
):
    """Load a Megatron-family checkpoint (any (tp, pp, dp) layout) into a
    live engine — the resume leg of the reshape story. The engine's own mesh
    resharding places the full tensors; the source topology is irrelevant."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths

    if not getattr(engine, "_initialized", False):
        raise RuntimeError("engine state must be initialized before load (run init_params)")
    path = _resolve_tag_dir(load_dir, tag)
    canon = read_reference_layout(path)

    def restack(groups: Dict[str, Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        flat: Dict[str, np.ndarray] = dict(groups.get(SHARED_KEY, {}))
        layer_keys = sorted((k for k in groups if k != SHARED_KEY), key=_key_order)
        if layer_keys:
            names = groups[layer_keys[0]].keys()
            for name in names:
                flat[LAYERS_PREFIX + name] = np.stack(
                    [groups[k][name] for k in layer_keys]
                )
        return flat

    def rebuild(template, flat: Dict[str, np.ndarray], cast=None):
        tpl_flat = _flatten_with_paths(template)
        missing = sorted(set(tpl_flat) - set(flat))
        if missing:
            raise KeyError(f"checkpoint is missing parameters: {missing[:5]} (+{len(missing) - 5 if len(missing) > 5 else 0} more)")

        def walk(prefix, node):
            if isinstance(node, dict):
                return {
                    k: walk(f"{prefix}/{k}" if prefix else str(k), v) for k, v in node.items()
                }
            if node is None:
                return None
            arr = flat[prefix]
            return arr.astype(cast) if cast is not None else arr

        return walk("", template)

    master_tpl = engine.get_master_params()
    module_flat = restack(canon["layers"])
    fp32_flat = restack(canon["fp32"]) if canon.get("fp32") else None

    put_p = jax.jit(lambda t: t, out_shardings=engine._param_shardings)
    compute_dtype = jnp.bfloat16 if engine.bfloat16_enabled() else (
        jnp.float16 if engine.fp16_enabled() else jnp.float32
    )
    engine._params = put_p(
        jax.tree_util.tree_map(
            jnp.asarray,
            rebuild(master_tpl, module_flat, cast=compute_dtype if engine.mixed_precision else None),
        )
    )
    if engine.mixed_precision:
        put_m = jax.jit(lambda t: t, out_shardings=engine._master_shardings)
        src = fp32_flat if fp32_flat is not None else module_flat
        engine._master = put_m(
            jax.tree_util.tree_map(jnp.asarray, rebuild(master_tpl, src, cast=np.float32))
        )
    else:
        engine._master = engine._params

    if (
        load_optimizer_states
        and canon.get("exp_avg")
        and engine._opt_state is not None
        and hasattr(engine._opt_state, "exp_avg")
    ):
        m_tree = rebuild(master_tpl, restack(canon["exp_avg"]), cast=np.float32)
        v_tree = rebuild(master_tpl, restack(canon["exp_avg_sq"]), cast=np.float32)
        new_state = engine._opt_state._replace(
            step=jnp.asarray(canon["global"].get("opt_step", 0), jnp.int32),
            exp_avg=jax.tree_util.tree_map(jnp.asarray, m_tree),
            exp_avg_sq=jax.tree_util.tree_map(jnp.asarray, v_tree),
        )
        put_o = jax.jit(lambda t: t, out_shardings=engine._opt_shardings)
        engine._opt_state = put_o(new_state)

    g = canon["global"]
    engine.global_steps = int(g.get("iteration", 0))
    engine.global_samples = int(g.get("global_samples", 0))
    engine.micro_steps = int(g.get("micro_steps", 0))
    engine.skipped_steps = int(g.get("skipped_steps", 0))
    if engine.lr_scheduler is not None and g.get("lr_scheduler"):
        engine.lr_scheduler.load_state_dict(g["lr_scheduler"])
    return path, {}
