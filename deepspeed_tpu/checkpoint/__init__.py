"""Universal checkpointing (reference: ``deepspeed/checkpoint/``)."""

from deepspeed_tpu.checkpoint.deepspeed_checkpoint import (
    DeepSpeedCheckpoint,
    convert_to_universal,
    load_hp_checkpoint_state,
    universal_param_names,
)
from deepspeed_tpu.checkpoint.reference_export import export_reference_checkpoint
from deepspeed_tpu.checkpoint.reference_ingest import (
    ingest_reference_checkpoint,
    ingest_universal_checkpoint,
    merge_reference_model_states,
    merge_reference_zero_fp32,
    read_universal_dir,
)
from deepspeed_tpu.checkpoint import constants
from deepspeed_tpu.checkpoint.constants import *  # noqa: F401,F403 - reference surface
from deepspeed_tpu.checkpoint.reshape_3d import (
    Model3DDescriptor,
    describe_checkpoint,
    export_megatron_checkpoint,
    load_megatron_checkpoint,
    read_reference_layout,
    reshape_checkpoint_3d,
    write_reference_layout,
)

# reference API-name aliases (deepspeed/checkpoint/__init__.py surface)
model_3d_desc = Model3DDescriptor
get_model_3d_descriptor = describe_checkpoint
from deepspeed_tpu.checkpoint.utils import (  # noqa: E402
    clone_tensors_for_torch_save,
    get_layer_ckpt_name_for_rank,
    get_model_ckpt_name_for_rank,
    get_zero_ckpt_name_for_rank,
)
from deepspeed_tpu.checkpoint.reshape_utils import (
    ReshapeMeg2D,
    merge_tp_slices,
    reshape_tp_degree,
    split_tp_slices,
)
