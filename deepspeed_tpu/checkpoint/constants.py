"""Symbolic checkpoint constants (reference
``deepspeed/checkpoint/constants.py`` — same names, so code written against
the reference's key/file-name vocabulary ports unchanged)."""

# Optimizer checkpoint keys
OPTIMIZER_STATE_DICT = "optimizer_state_dict"
FP32_GROUPS = "fp32_groups"
FP32_FLAT_GROUPS = "fp32_flat_groups"
BASE_OPTIMIZER_STATE = "base_optimizer_state"
SINGLE_PARTITION_OF_FP32_GROUPS = "single_partition_of_fp32_groups"
GROUP_PADDINGS = "group_paddings"
PARTITION_COUNT = "partition_count"
ZERO_STAGE = "zero_stage"
CLIP_GRAD = "clip_grad"
FP32_WEIGHT_KEY = "fp32"

# Module checkpoint keys
PARAM = "param"
PARAM_SHAPES = "param_shapes"
BUFFER_NAMES = "buffer_names"
FROZEN_PARAM_SHAPES = "frozen_param_shapes"
FROZEN_PARAM_FRAGMENTS = "frozen_param_fragments"

# Checkpoint naming constants
MODEL_FILE_PREFIX = "mp_rank_"
ZERO_FILE_PREFIX = "zero_pp_rank_"
OPTIM_FILE_SUFFIX = "_optim_states.pt"
MODEL_FILE_SUFFIX = "_model_states.pt"
LAYER_FILE_PREFIX = "layer_"
BF16_ZERO_FILE_PREFIX = "bf16_" + ZERO_FILE_PREFIX
FP16_ZERO_FILE_PREFIX = "fp16_" + ZERO_FILE_PREFIX

# Checkpoint utility keys
DS_VERSION = "ds_version"

# Universal Checkpoint keys
UNIVERSAL_CHECKPOINT_INFO = "universal_checkpoint_info"
UNIVERSAL_CHECKPOINT_VERSION_KEY = "universal_checkpoint_version"
UNIVERSAL_CHECKPOINT_VERSION_VALUE = 0.2

# Vocabulary padding
VOCAB_DIVISIBILITY_PADDING_TENSOR = "vocab_divisibility_padding_tensor"
PADDED_VOCAB_SIZE = "padded_vocab_size"
ORIGINAL_VOCAB_SIZE = "original_vocab_size"

# Parameter splitting/merging
PARAM_SLICE_MAPPINGS = "param_slice_mappings"
CAT_DIM = "cat_dim"

# Regex list of parameters that require special handling
VOCABULARY_PARAMETER_PATTERNS = "vocabulary_parameter_patterns"
PIPELINE_REPLICATED_PARAMETER_PATTERNS = "pipeline_replicated_parameter_patterns"
PARAMETER_TO_AVERAGE_PATTERNS = "parameter_to_average_patterns"
PARAMETER_WITH_ROW_PARALLELISM_PATTERNS = "parameter_with_row_parallelism_patterns"
