"""Checkpoint path helpers (reference ``deepspeed/checkpoint/utils.py``)."""

from __future__ import annotations

import os

import numpy as np

from deepspeed_tpu.checkpoint.constants import (
    MODEL_FILE_PREFIX,
    MODEL_FILE_SUFFIX,
    OPTIM_FILE_SUFFIX,
    ZERO_FILE_PREFIX,
)


def get_model_ckpt_name_for_rank(base_folder: str, mp_rank_str: str) -> str:
    return os.path.join(base_folder, MODEL_FILE_PREFIX + mp_rank_str + MODEL_FILE_SUFFIX)


def get_zero_ckpt_name_for_rank(base_folder: str, dp_rank: int, mp_rank: int) -> str:
    return os.path.join(
        base_folder,
        f"{ZERO_FILE_PREFIX}{dp_rank}_{MODEL_FILE_PREFIX}{mp_rank:02d}{OPTIM_FILE_SUFFIX}",
    )


def get_layer_ckpt_name_for_rank(base_folder: str, layer_id: str, tp_rank: int) -> str:
    return os.path.join(base_folder, f"{layer_id}-model_{tp_rank:02d}{MODEL_FILE_SUFFIX}")


def clone_tensors_for_torch_save(item, device=None):
    """(reference utils.py:42) The reference clones tensors so torch.save
    doesn't serialize whole flat-buffer storages. JAX arrays copy on
    device_get, so here this is a host-materialization walk: every array
    leaf becomes its own compact host copy."""
    if hasattr(item, "detach"):  # torch tensor passing through
        out = item.detach().clone()
        return out.to(device) if device is not None else out
    if isinstance(item, (list, tuple)):
        return type(item)(clone_tensors_for_torch_save(v, device) for v in item)
    if isinstance(item, dict):
        return type(item)({k: clone_tensors_for_torch_save(v, device) for k, v in item.items()})
    if hasattr(item, "__array__"):
        return np.array(item)  # compact host copy (np.array always copies)
    return item
