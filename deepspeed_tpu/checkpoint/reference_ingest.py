"""Ingest reference-layout (DeepSpeed torch) checkpoints.

The reference saves per-rank torch pickles (``deepspeed/runtime/engine.py:2582-2588``):

* ``{tag}/mp_rank_{mp:02d}_model_states.pt`` — module weights, one file per
  model-parallel rank (TP-sharded tensors), replicated across dp;
* ``{tag}/zero_pp_rank_{dp}_mp_rank_{mp:02d}_optim_states.pt`` — the ZeRO
  stage-1/2 optimizer partitions: each dp rank's slice of the flat fp32
  master per param group (``single_partition_of_fp32_groups``,
  stage_1_and_2.py:2035), with per-param shapes recorded in the model file
  (``param_shapes``).

This module rebuilds full tensors from that layout — TP shards merged along
per-name axes supplied by the architecture's injection policy, dp-flat fp32
partitions concatenated and re-split by the recorded shapes (the
``ds_to_universal.py`` algorithm) — and converts the merged state dict onto
the fused TPU model via the same policy used for HF injection. Loading into
a *different* mesh needs nothing further: params are global arrays and the
GSPMD partitioner reshards on placement.
"""

from __future__ import annotations

import glob
import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.checkpoint.reshape_utils import merge_tp_slices
from deepspeed_tpu.module_inject.containers import policy_for
from deepspeed_tpu.utils.logging import log_dist

# torch [out, in] Linear convention: "column"-parallel shards the OUT dim
# (axis 0), "row"-parallel the IN dim (axis 1)
_MEGATRON_TP_AXES = [
    (r"query_key_value\.(weight|bias)$", 0),
    (r"dense_h_to_4h\.(weight|bias)$", 0),
    (r"attention\.dense\.weight$", 1),
    (r"dense_4h_to_h\.weight$", 1),
    (r"word_embeddings\.weight$", 0),
]

_HF_LLAMA_TP_AXES = [
    (r"(q|k|v)_proj\.weight$", 0),
    (r"(gate|up)_proj\.weight$", 0),
    (r"(o|down)_proj\.weight$", 1),
    (r"embed_tokens\.weight$", 0),
    (r"lm_head\.weight$", 0),
]


def tp_merge_axis(name: str, model_type: str) -> Optional[int]:
    """Concat axis for one param's TP shards; None = replicated (take rank 0).
    The reference records no sharding metadata in the files — the axis is a
    property of the architecture (module_inject policy knowledge)."""
    table = (
        _HF_LLAMA_TP_AXES
        if model_type in ("llama", "mistral")
        else _MEGATRON_TP_AXES
    )
    for pattern, axis in table:
        if re.search(pattern, name):
            return axis
    return None


def _torch_load(path: str):
    import torch

    return torch.load(path, map_location="cpu", weights_only=False)


def _to_numpy(t, preserve_bf16: bool = False) -> np.ndarray:
    """Torch tensor → numpy. Default: promote to fp32 (lossless for bf16;
    the merge paths want one dtype). ``preserve_bf16`` keeps bf16 as numpy's
    extension dtype so dtype-preserving paths (reshape_3d) round-trip."""
    if hasattr(t, "detach"):
        t = t.detach().cpu()
        if preserve_bf16 and str(t.dtype) == "torch.bfloat16":
            import jax.numpy as jnp
            import torch

            return np.asarray(jnp.asarray(t.to(torch.float32).numpy()).astype(jnp.bfloat16))
        return t.float().numpy()
    return np.asarray(t)


def _resolve_tag_dir(ckpt_dir: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
    path = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint dir at {path}")
    return path


def _model_state_files(path: str) -> List[str]:
    if glob.glob(os.path.join(path, "layer_*-model_*-model_states.pt")):
        raise NotImplementedError(
            "pipeline-partitioned reference checkpoints (per-layer "
            "layer_XX-model_YY files) are not ingestable yet; consolidate "
            "with the reference's ds_to_universal first"
        )
    files = sorted(glob.glob(os.path.join(path, "mp_rank_*_model_states.pt")))
    if not files:
        # stage-3 reference checkpoints scatter module states across dp ranks
        # (zero_pp_rank_{dp}_mp_rank_{mp}_model_states.pt) — name the layout
        # instead of a bare FileNotFoundError
        if glob.glob(os.path.join(path, "*zero_pp_rank_*_model_states.pt")):
            raise NotImplementedError(
                "ZeRO stage-3 reference checkpoints (per-dp-rank "
                "zero_pp_rank_*_model_states.pt module files) are not "
                "ingestable; consolidate with the reference's "
                "zero_to_fp32.py or ds_to_universal first"
            )
        raise FileNotFoundError(f"no mp_rank_*_model_states.pt under {path}")
    return files


def merge_reference_model_states(
    ckpt_dir: str, model_type: str, tag: Optional[str] = None
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Full (TP-merged) torch state dict + meta from a reference checkpoint."""
    path = _resolve_tag_dir(ckpt_dir, tag)
    files = _model_state_files(path)
    states = [_torch_load(f) for f in files]
    modules = [s.get("module", s) for s in states]
    tp = len(files)
    merged: Dict[str, np.ndarray] = {}
    for name in modules[0]:
        shards = [_to_numpy(m[name]) for m in modules]
        axis = tp_merge_axis(name, model_type) if tp > 1 else None
        if axis is None:
            merged[name] = shards[0]
        else:
            merged[name] = merge_tp_slices(shards, axis=axis)
    meta = {
        "tp_degree": tp,
        "iteration": int(states[0].get("global_steps") or states[0].get("iteration") or 0),
        "param_shapes": states[0].get("param_shapes"),
        "dp_world_size": states[0].get("dp_world_size"),
    }
    return merged, meta


def merge_reference_zero_fp32(
    ckpt_dir: str, model_type: str, tag: Optional[str] = None
) -> Dict[str, np.ndarray]:
    """fp32 masters from the ZeRO stage-1/2 optimizer shards, keyed by the
    torch param names (the ``ds_to_universal.py`` reconstruction): for each
    mp rank, concatenate every dp rank's flat partition per group and
    re-split by the ``param_shapes`` recorded in the model file; then
    TP-merge across mp ranks."""
    path = _resolve_tag_dir(ckpt_dir, tag)
    model_files = _model_state_files(path)
    per_mp: List[Dict[str, np.ndarray]] = []
    for mp, mf in enumerate(model_files):
        shapes_groups = _torch_load(mf).get("param_shapes")
        if shapes_groups is None:
            raise ValueError(
                f"{mf} records no param_shapes; cannot reconstruct fp32 "
                "masters from flat ZeRO partitions"
            )
        # bf16 runs prefix the shards (engine _get_zero_ckpt_prefix):
        # bf16_zero_pp_rank_{dp}_mp_rank_{mp}_optim_states.pt
        zfiles = sorted(
            glob.glob(os.path.join(path, f"zero_pp_rank_*_mp_rank_{mp:02d}_optim_states.pt"))
            or glob.glob(os.path.join(path, f"bf16_zero_pp_rank_*_mp_rank_{mp:02d}_optim_states.pt")),
            key=lambda p: int(re.search(r"zero_pp_rank_(\d+)_", p).group(1)),
        )
        if not zfiles:
            raise FileNotFoundError(
                f"no (bf16_)zero_pp_rank_*_mp_rank_{mp:02d} files under {path}"
            )
        zstates = [_torch_load(f)["optimizer_state_dict"] for f in zfiles]
        n_groups = len(shapes_groups)
        out: Dict[str, np.ndarray] = {}
        for g in range(n_groups):
            flat = np.concatenate(
                [_to_numpy(z["single_partition_of_fp32_groups"][g]).ravel() for z in zstates]
            )
            offset = 0
            for name, shape in shapes_groups[g].items():
                n = int(np.prod(shape))
                out[name] = flat[offset : offset + n].reshape(tuple(shape))
                offset += n
            # anything past offset is the dp-divisibility padding
        per_mp.append(out)
    if len(per_mp) == 1:
        return per_mp[0]
    merged: Dict[str, np.ndarray] = {}
    for name in per_mp[0]:
        axis = tp_merge_axis(name, model_type)
        shards = [m[name] for m in per_mp]
        merged[name] = shards[0] if axis is None else merge_tp_slices(shards, axis=axis)
    return merged


def ingest_reference_checkpoint(
    ckpt_dir: str,
    model_config: Any,
    model_type: Optional[str] = None,
    tag: Optional[str] = None,
    use_zero_fp32: bool = True,
    dtype: Optional[str] = None,
):
    """Reference 3D (tp, dp[, pp via consolidation]) checkpoint → fused TPU
    model + param tree, loadable into ANY mesh (reference
    ``reshape_meg_2d.py`` + ``universal_checkpoint.py:95`` use case).

    Returns ``(ds_model, params, meta)``. With ``use_zero_fp32`` the weights
    come from the reconstructed fp32 masters (exact, like the reference's
    universal path); otherwise from the bf16/fp16 module states."""
    from deepspeed_tpu.module_inject.replace_module import replace_transformer_layer

    mtype = model_type or getattr(model_config, "model_type", None)
    if mtype is None:
        raise ValueError("model_type is required (none found on model_config)")
    sd, meta = merge_reference_model_states(ckpt_dir, mtype, tag)
    if use_zero_fp32:
        try:
            fp32 = merge_reference_zero_fp32(ckpt_dir, mtype, tag)
            sd = {**sd, **fp32}
            meta["weights_from"] = "zero_fp32_masters"
        except (FileNotFoundError, ValueError) as e:
            log_dist(
                f"use_zero_fp32 requested but falling back to module states: {e}",
                ranks=[0],
                level=logging.WARNING,
            )
            meta["weights_from"] = "module_states"
    else:
        meta["weights_from"] = "module_states"
    ds_model, _ = replace_transformer_layer(model_config=model_config, dtype=dtype)
    policy = policy_for(mtype)
    params = policy.convert_weights(sd, ds_model.config)
    log_dist(
        f"ingested reference checkpoint: tp={meta['tp_degree']} "
        f"iteration={meta['iteration']} weights={meta['weights_from']}",
        ranks=[0],
    )
    return ds_model, params, meta


def read_universal_dir(universal_dir: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Read a reference *universal* checkpoint directory (the layout
    ``ds_to_universal.py`` writes and ``universal_checkpoint.py:12``
    ``load_hp_checkpoint_state`` reads): one folder per parameter holding
    ``fp32.pt`` plus optimizer-state files (``exp_avg.pt``,
    ``exp_avg_sq.pt``), each a torch file with the full (TP-merged,
    padding-stripped) tensor under the ``param`` key (raw-tensor files are
    tolerated). Returns ``{key: {param_name: ndarray}}`` for every key
    found, e.g. ``{"fp32": {...}, "exp_avg": {...}}``."""
    root = universal_dir
    zero = os.path.join(root, "zero")
    if os.path.isdir(zero):
        root = zero
    if not os.path.isdir(root):
        raise FileNotFoundError(
            f"{universal_dir} is not a universal checkpoint directory "
            "(expected <dir>/zero/<param>/fp32.pt folders)"
        )
    out: Dict[str, Dict[str, np.ndarray]] = {}
    found_any = False
    for name in sorted(os.listdir(root)):
        folder = os.path.join(root, name)
        if not os.path.isdir(folder):
            continue
        for fn in sorted(os.listdir(folder)):
            if not fn.endswith(".pt"):
                continue
            key = fn[:-3]
            blob = _torch_load(os.path.join(folder, fn))
            tensor = blob.get("param") if isinstance(blob, dict) else blob
            if tensor is None:
                continue
            out.setdefault(key, {})[name] = _to_numpy(tensor)
            found_any = True
    if not found_any:
        raise FileNotFoundError(
            f"no <param>/<key>.pt files under {universal_dir} — not a "
            "universal checkpoint"
        )
    return out


def ingest_universal_checkpoint(
    universal_dir: str,
    model_config: Any,
    model_type: Optional[str] = None,
    load_optimizer: bool = True,
    dtype: Optional[str] = None,
):
    """Reference universal checkpoint (``ds_to_universal`` output) → fused
    TPU model + params (+ Adam moments), loadable into ANY mesh.

    The universal format already carries full, TP-merged, padding-free fp32
    tensors per parameter — so unlike ``ingest_reference_checkpoint`` there
    is no shard merging; the per-architecture policy walk
    (``module_inject/containers.py``) maps torch names into the fused
    layout, and because the optimizer moments are shaped exactly like their
    parameters, the SAME walk converts ``exp_avg``/``exp_avg_sq`` into a
    moments tree aligned with the param tree.

    Returns ``(ds_model, params, moments)`` where ``moments`` is
    ``{"exp_avg": tree, "exp_avg_sq": tree}`` (or None)."""
    from deepspeed_tpu.module_inject.replace_module import replace_transformer_layer

    mtype = model_type or getattr(model_config, "model_type", None)
    if mtype is None:
        raise ValueError("model_type is required (none found on model_config)")
    state = read_universal_dir(universal_dir)
    if "fp32" not in state:
        raise ValueError(
            f"universal checkpoint at {universal_dir} has no fp32 weights"
        )
    ds_model, _ = replace_transformer_layer(model_config=model_config, dtype=dtype)
    policy = policy_for(mtype)
    params = policy.convert_weights(dict(state["fp32"]), ds_model.config)
    moments = None
    if load_optimizer and "exp_avg" in state and "exp_avg_sq" in state:
        moments = {
            "exp_avg": policy.convert_weights(dict(state["exp_avg"]), ds_model.config),
            "exp_avg_sq": policy.convert_weights(
                dict(state["exp_avg_sq"]), ds_model.config
            ),
        }
    log_dist(
        f"ingested universal checkpoint: {len(state['fp32'])} tensors, "
        f"moments={'yes' if moments else 'no'}",
        ranks=[0],
    )
    return ds_model, params, moments
