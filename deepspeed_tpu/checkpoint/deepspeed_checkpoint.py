"""Universal checkpoint.

Counterpart of the reference's ``deepspeed/checkpoint/``
(``DeepSpeedCheckpoint`` deepspeed_checkpoint.py, per-param hp fragments
``universal_checkpoint.py:95``, engine flag ``load_universal_checkpoint``):
a topology-agnostic on-disk format — one record per parameter holding the
full fp32 master plus full optimizer-state tensors — loadable into ANY
(tp, pp, dp) layout.

deepspeed_tpu checkpoints are already *mesh*-agnostic (orbax global
arrays reshard on load), so the universal format's job here is
cross-FRAMEWORK and cross-run portability: a flat ``.npz`` per state kind
with ``/``-joined param paths, produced by :func:`convert_to_universal` and
consumed by ``engine.load_universal_checkpoint``-style flows or the
reference's own tooling."""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

ZERO_FILE = "zero_universal.npz"
META_FILE = "universal_meta.json"
PARAM_SHAPE_KEY = "param_shapes"


class DeepSpeedCheckpoint:
    """Inspect a deepspeed_tpu checkpoint dir (reference
    ``DeepSpeedCheckpoint`` surface: degree accessors + state access)."""

    def __init__(self, ckpt_dir: str, tp_degree: Optional[int] = None, pp_degree: Optional[int] = None):
        self.ckpt_dir = ckpt_dir
        tag = None
        latest = os.path.join(ckpt_dir, "latest")
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
        self.tag = tag
        self.path = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
        from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
            OrbaxCheckpointEngine,
        )

        self.state = OrbaxCheckpointEngine().load(self.path)
        # target degrees are free under GSPMD; recorded for parity/tools
        self.tp_degree = tp_degree or 1
        self.pp_degree = pp_degree or 1

    def get_iteration(self) -> int:
        return int(self.state.get("global_steps", 0))

    def get_module(self) -> Dict[str, Any]:
        return self.state["module"]

    def get_zero_checkpoint_state(self) -> Optional[Dict[str, Any]]:
        return self.state.get("optimizer")

    def show_tp_degree(self) -> int:
        return self.tp_degree

    def show_pp_degree(self) -> int:
        return self.pp_degree


def _flat(tree, prefix="") -> Dict[str, np.ndarray]:
    from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths

    return {k: np.asarray(v) for k, v in _flatten_with_paths(tree).items() if v is not None}


def convert_to_universal(ckpt_dir: str, out_dir: str, tag: Optional[str] = None) -> str:
    """Produce the universal format (reference ``ds_to_universal.py``):
    fp32 master + exp_avg/exp_avg_sq per param, topology-free."""
    from deepspeed_tpu.utils.zero_to_fp32 import get_fp32_state_dict_from_zero_checkpoint

    os.makedirs(out_dir, exist_ok=True)
    ckpt = DeepSpeedCheckpoint(ckpt_dir)
    fp32 = get_fp32_state_dict_from_zero_checkpoint(ckpt_dir, tag)

    records: Dict[str, np.ndarray] = {}
    for name, w in fp32.items():
        records[f"{name}::fp32"] = w
    opt = ckpt.get_zero_checkpoint_state()
    if isinstance(opt, dict) and "host_offload" in opt:
        names = list(fp32.keys())
        for name, per in zip(names, opt["host_offload"]["leaves"]):
            for key in ("exp_avg", "exp_avg_sq"):
                full = np.zeros(fp32[name].shape, np.float32)
                for rec in per:
                    sl = tuple(slice(a, b) for a, b in rec["index"])
                    full[sl] = np.asarray(rec[key], np.float32).reshape(full[sl].shape)
                records[f"{name}::{key}"] = full
    elif isinstance(opt, dict):
        for key in ("exp_avg", "exp_avg_sq"):
            if key in opt and opt[key] is not None:
                for name, v in _flat(opt[key]).items():
                    records[f"{name}::{key}"] = np.asarray(v, np.float32)

    out_file = os.path.join(out_dir, ZERO_FILE)
    np.savez(out_file, **records)
    meta = {
        "iteration": ckpt.get_iteration(),
        PARAM_SHAPE_KEY: {k: list(v.shape) for k, v in fp32.items()},
        "source": os.path.abspath(ckpt_dir),
    }
    from deepspeed_tpu.runtime.checkpoint_engine.atomic import atomic_write_text

    atomic_write_text(os.path.join(out_dir, META_FILE), json.dumps(meta, indent=2))
    return out_file


def load_hp_checkpoint_state(universal_dir: str, name: str) -> Dict[str, np.ndarray]:
    """Per-param hp fragment load (reference universal_checkpoint.py:95):
    returns {fp32, exp_avg, exp_avg_sq} for one parameter path."""
    data = np.load(os.path.join(universal_dir, ZERO_FILE))
    out = {}
    for key in ("fp32", "exp_avg", "exp_avg_sq"):
        k = f"{name}::{key}"
        if k in data:
            out[key] = data[k]
    if not out:
        raise KeyError(f"no universal records for parameter {name!r}")
    return out


def universal_param_names(universal_dir: str) -> List[str]:
    data = np.load(os.path.join(universal_dir, ZERO_FILE))
    return sorted({k.split("::")[0] for k in data.files})
