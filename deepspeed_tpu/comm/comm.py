"""Device-agnostic collectives facade.

TPU-native counterpart of the reference's ``deepspeed/comm/comm.py`` (the
module-level API mirroring torch.distributed: ``init_distributed`` comm.py:604,
``all_reduce`` :483, ``all_to_all_single`` :331, ``barrier``, profiling
decorator ``timed_op`` :101, ``log_summary`` :422).

Two planes exist on TPU:

* **Compute plane** (the hot path): collectives inside jitted programs are
  emitted by the GSPMD partitioner from sharding annotations, or written
  explicitly with ``jax.lax`` collectives under ``shard_map`` (see
  ``deepspeed_tpu.comm.collectives``). Nothing in this module runs there.
* **Control plane** (this module): process-level rendezvous
  (``jax.distributed.initialize``), eager cross-process reductions of small
  host values (loss averages, overflow flags, checkpoint tags), barriers and
  object broadcast. These ride DCN, exactly like the reference's Gloo/TCP
  store usage for control data.

Rank/world-size semantics: on TPU there is one process per *host* and the
devices hang off a mesh, so ``get_rank``/``get_world_size`` are process-level
(matching the launcher), while ``get_device_count``/``get_global_device_count``
expose chip counts for sharding math.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.comm.reduce_op import ReduceOp
from deepspeed_tpu.utils.comms_logging import CommsLogger, calc_bw_log
from deepspeed_tpu.utils.logging import logger

# module state -------------------------------------------------------------
cdb_initialized = False
comms_logger = CommsLogger()
_timers = {}
# unified-tracing hookup (profiling/tracer.py): the engines hand their
# tracer here so every control-plane collective lands on the same timeline
# as the step phases. Module-level like comms_logger — the latest engine
# wins, which matches the one-engine-per-process deployment shape.
_comm_tracer = None


def set_comm_tracer(tracer) -> None:
    """Route ``comm.*`` spans (one per eager control-plane collective)
    into the given tracer; ``None`` detaches."""
    global _comm_tracer
    _comm_tracer = tracer


class DSCommError(RuntimeError):
    pass


def _jax():
    import jax

    return jax


# -- init ------------------------------------------------------------------
def init_distributed(
    dist_backend: str = "xla",
    auto_mpi_discovery: bool = True,  # noqa: ARG001 - kept for API parity
    distributed_port: int = 29500,
    verbose: bool = True,
    timeout=None,  # noqa: ARG001
    init_method: Optional[str] = None,  # noqa: ARG001
    dist_init_required: Optional[bool] = None,  # noqa: ARG001
    config=None,  # noqa: ARG001
    rank: int = -1,
    world_size: int = -1,
) -> None:
    """Initialize the process-level distributed runtime.

    Multi-host coordinates come from (in priority order) explicit args, the
    standard JAX cluster envs, or DeepSpeed-style ``MASTER_ADDR``/``RANK``/
    ``WORLD_SIZE`` envs set by the launcher. Single-process if none present.
    """
    global cdb_initialized
    if cdb_initialized:
        return
    if dist_backend not in ("xla", "nccl", "gloo", "ccl", "hccl"):
        raise DSCommError(f"unknown dist backend {dist_backend!r}")

    jax = _jax()
    coordinator = os.environ.get("COORDINATOR_ADDRESS")
    env_world = int(os.environ.get("WORLD_SIZE", world_size if world_size > 0 else 1))
    env_rank = int(os.environ.get("RANK", rank if rank >= 0 else 0))
    if coordinator is None and env_world > 1 and "MASTER_ADDR" in os.environ:
        coordinator = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', distributed_port)}"
    if coordinator is not None and env_world > 1:
        if verbose:
            logger.info(
                f"Initializing jax.distributed: coordinator={coordinator} "
                f"process={env_rank}/{env_world}"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env_world,
            process_id=env_rank,
        )
    elif verbose:
        logger.info("Single-process distributed runtime (no coordinator found)")
    cdb_initialized = True


def is_initialized() -> bool:
    return cdb_initialized


def destroy_process_group(group=None) -> None:  # noqa: ARG001
    global cdb_initialized
    try:
        _jax().distributed.shutdown()
    except Exception:
        pass
    cdb_initialized = False


# -- topology queries ------------------------------------------------------
def get_rank(group=None) -> int:  # noqa: ARG001
    try:
        return _jax().process_index()
    except Exception:
        return 0


def get_world_size(group=None) -> int:
    if group is not None and hasattr(group, "size"):
        return group.size
    try:
        return _jax().process_count()
    except Exception:
        return 1


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))


def get_device_count() -> int:
    return _jax().local_device_count()


def get_global_device_count() -> int:
    return _jax().device_count()


def get_all_ranks_from_group(group=None) -> List[int]:
    if group is not None and hasattr(group, "ranks"):
        return list(group.ranks)
    return list(range(get_world_size()))


# -- profiling decorator ---------------------------------------------------
def timed_op(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        prof = getattr(comms_logger, "prof_all", False) or func.__name__ in comms_logger.prof_ops
        trace = _comm_tracer is not None and _comm_tracer.enabled
        if not (prof or trace):
            return func(*args, **kwargs)
        start = time.perf_counter()
        result = func(*args, **kwargs)
        if result is not None and hasattr(result, "block_until_ready"):
            result.block_until_ready()
        end = time.perf_counter()
        nbytes = _nbytes(args)
        if prof:
            comms_logger.append(func.__name__, func.__name__, (end - start) * 1000.0, nbytes)
        if trace:
            _comm_tracer.add_span(f"comm.{func.__name__}", start, end, bytes=nbytes)
        return result

    return wrapper


def _nbytes(args) -> int:
    """Payload size: the first array-like positional arg (skips output lists)."""
    for x in args:
        try:
            return int(x.size * x.dtype.itemsize)
        except Exception:
            continue
    return 0


def configure(config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None) -> None:
    if config is not None and hasattr(config, "comms_config"):
        comms_logger.configure(config.comms_config)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def log_summary(show_straggler: bool = False):
    return comms_logger.log_all(print_log=True, show_straggler=show_straggler)


# -- eager control-plane collectives ---------------------------------------
def _multihost():
    from jax.experimental import multihost_utils

    return multihost_utils


def barrier(group=None, name: str = "") -> None:  # noqa: ARG001
    if get_world_size() > 1:
        _multihost().sync_global_devices(name or "ds_barrier")


@timed_op
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False):  # noqa: ARG001
    """Eager cross-process reduction of a host/global value; returns the result.

    JAX arrays are immutable so this returns rather than mutating in place;
    engine call-sites assign the result back.
    """
    arr = np.asarray(tensor)
    if get_world_size() == 1:
        return arr
    gathered = _multihost().process_allgather(arr)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = gathered.sum(axis=0)
        if op == ReduceOp.AVG:
            out = out / get_world_size()
    elif op == ReduceOp.MAX:
        out = gathered.max(axis=0)
    elif op == ReduceOp.MIN:
        out = gathered.min(axis=0)
    elif op == ReduceOp.PRODUCT:
        out = gathered.prod(axis=0)
    else:
        raise DSCommError(f"unsupported eager reduce op {op}")
    return out


@timed_op
def all_gather(tensor_list: Optional[list], tensor, group=None, async_op: bool = False):  # noqa: ARG001
    arr = np.asarray(tensor)
    if get_world_size() == 1:
        gathered = arr[None]
    else:
        gathered = _multihost().process_allgather(arr)
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(list(gathered))
    return gathered


@timed_op
def broadcast(tensor, src: int = 0, group=None, async_op: bool = False):  # noqa: ARG001
    if get_world_size() == 1:
        return np.asarray(tensor)
    return _multihost().broadcast_one_to_all(np.asarray(tensor), is_source=get_rank() == src)


def broadcast_object_list(object_list: list, src: int = 0, group=None) -> None:  # noqa: ARG001
    import pickle

    if get_world_size() == 1:
        return
    payload = pickle.dumps(object_list) if get_rank() == src else b""
    # length-prefix exchange, then payload broadcast
    length = int(broadcast(np.array([len(payload)], dtype=np.int64), src=src)[0])
    buf = np.zeros(length, dtype=np.uint8)
    if get_rank() == src:
        buf[:] = np.frombuffer(payload, dtype=np.uint8)
    out = _multihost().broadcast_one_to_all(buf, is_source=get_rank() == src)
    object_list[:] = pickle.loads(out.tobytes())


def all_gather_object(obj: Any) -> List[Any]:
    import pickle

    if get_world_size() == 1:
        return [obj]
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    lengths = all_reduce(
        np.eye(get_world_size(), dtype=np.int64)[get_rank()] * len(payload), op=ReduceOp.SUM
    )
    maxlen = int(lengths.max())
    padded = np.zeros(maxlen, dtype=np.uint8)
    padded[: len(payload)] = payload
    gathered = _multihost().process_allgather(padded)
    return [pickle.loads(gathered[i, : int(lengths[i])].tobytes()) for i in range(get_world_size())]


# torch.distributed capability probes mirrored for API parity --------------
def has_all_gather_into_tensor() -> bool:
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def has_coalescing_manager() -> bool:
    # GSPMD fuses collectives; a coalescing manager is implicit.
    return True


def get_global_rank(group=None, group_rank: int = 0) -> int:
    """Translate a rank WITHIN ``group`` to its global rank (reference
    facade contract): group handles carry their rank list."""
    if group is not None and hasattr(group, "ranks"):
        return int(group.ranks[group_rank])
    return group_rank


def new_group(ranks: Sequence[int]):
    """Process groups are mesh axes on TPU; return a lightweight handle."""

    class _Group:
        def __init__(self, ranks):
            self.ranks = list(ranks)
            self.size = len(self.ranks)

    return _Group(ranks)


# MPI / cloud env discovery (reference comm.py:671,726,758) -----------------
def mpi_discovery(distributed_port: int = 29500, verbose: bool = True) -> None:
    """Populate RANK/WORLD_SIZE/MASTER_* from OpenMPI envs when present."""
    ompi_rank = os.environ.get("OMPI_COMM_WORLD_RANK")
    if ompi_rank is None:
        return
    os.environ.setdefault("RANK", ompi_rank)
    os.environ.setdefault("WORLD_SIZE", os.environ.get("OMPI_COMM_WORLD_SIZE", "1"))
    os.environ.setdefault("LOCAL_RANK", os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", "0"))
    os.environ.setdefault("MASTER_PORT", str(distributed_port))
    if verbose:
        logger.info(
            f"MPI discovery: rank={os.environ['RANK']} world={os.environ['WORLD_SIZE']}"
        )


# -- remaining torch.distributed-shaped surface (reference comm.py) --------
# Control-plane implementations: correct semantics composed from the
# rendezvous primitives above. The HOT paths never come through here — in
# compiled programs the GSPMD partitioner / lax collectives own the wire.


def is_available() -> bool:
    """Parity with torch.distributed.is_available (comm facade probe)."""
    return True


_world_group_cache = {}


def get_world_group():
    """The default (world) group handle (reference get_world_group) —
    cached so identity checks and hot loops don't re-allocate."""
    n = get_world_size()
    if n not in _world_group_cache:
        _world_group_cache[n] = new_group(list(range(n)))
    return _world_group_cache[n]


@timed_op
def reduce(tensor, dst: int = 0, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False):  # noqa: ARG001
    """Reference ``reduce``: result is defined on ``dst``. The SPMD control
    plane computes it everywhere (an all-reduce); returns the reduced value
    on every rank — a superset of the contract."""
    return all_reduce(tensor, op=op, group=group)


@timed_op
def gather(tensor, gather_list: Optional[list] = None, dst: int = 0, group=None, async_op: bool = False):  # noqa: ARG001
    """Reference ``gather``: ``gather_list`` is filled on ``dst`` (here: on
    every rank — the all-gather superset)."""
    gathered = all_gather(None, tensor, group=group)
    if gather_list is not None and get_rank() == dst:
        gather_list.clear()
        gather_list.extend(list(gathered))
    return gathered


@timed_op
def all_gather_into_tensor(output_tensor, input_tensor, group=None, async_op: bool = False):  # noqa: ARG001
    """Flat-output all-gather (reference comm.py all_gather_into_tensor /
    torch dist.all_gather_into_tensor). Returns the stacked array (JAX
    arrays are immutable; callers assign)."""
    gathered = all_gather(None, input_tensor, group=group)
    return np.concatenate([np.asarray(g).reshape(-1) for g in gathered]).reshape(
        np.shape(output_tensor)
    )


def allgather_fn(output_tensor, input_tensor, group=None, debug=False):  # noqa: ARG001
    return all_gather_into_tensor(output_tensor, input_tensor, group=group)


@timed_op
def reduce_scatter(output, input_list, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False):  # noqa: ARG001
    """Reduce a per-rank list and keep this rank's entry."""
    stacked = np.stack([np.asarray(t) for t in input_list])
    reduced = all_reduce(stacked, op=op, group=group)
    return reduced[get_rank()]


@timed_op
def reduce_scatter_tensor(output_tensor, tensor, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False):  # noqa: ARG001
    """Flat-tensor reduce-scatter (this rank's contiguous chunk)."""
    reduced = all_reduce(np.asarray(tensor).reshape(-1), op=op, group=group)
    chunk = reduced.reshape(get_world_size(), -1)[get_rank()]
    return chunk.reshape(np.shape(output_tensor))


def reduce_scatter_fn(output_tensor, tensor, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False, debug=False):  # noqa: ARG001
    return reduce_scatter_tensor(output_tensor, tensor, op=op, group=group)


@timed_op
def all_to_all_single(output, tensor, output_split_sizes=None, input_split_sizes=None, group=None, async_op: bool = False):  # noqa: ARG001
    """Each rank sends chunk i of its input to rank i (reference
    comm.py:331). Control-plane: composed as gather + select; the training
    paths' all-to-alls (MoE dispatch, Ulysses) are ``lax.all_to_all`` inside
    the compiled programs (``comm/collectives.py``), not this."""
    world, rank = get_world_size(), get_rank()
    arr = np.asarray(tensor)
    if input_split_sizes is None:
        chunks = np.split(arr, world, axis=0)
    else:
        idx = np.cumsum(input_split_sizes)[:-1]
        chunks = np.split(arr, idx, axis=0)
    # one rendezvous: gather every rank's chunk list, then keep the chunk
    # each source addressed to this rank
    full = all_gather_object([np.asarray(c) for c in chunks])
    received = [full[src][rank] for src in range(world)]
    return np.concatenate(received, axis=0)


@timed_op
def all_to_all(output_tensor_list, input_tensor_list, group=None, async_op: bool = False):  # noqa: ARG001
    """List form of all_to_all_single."""
    world, rank = get_world_size(), get_rank()
    full = all_gather_object([np.asarray(t) for t in input_tensor_list])
    received = [full[src][rank] for src in range(world)]
    if output_tensor_list is not None:
        output_tensor_list[:] = received
    return received


def all_reduce_coalesced(tensors, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False):  # noqa: ARG001
    """One rendezvous for a list of tensors (reference has_all_reduce_coalesced
    capability). Each tensor keeps its own dtype — a flat concat would upcast
    mixed lists (int flags next to f32 grads) to a common type."""
    arrs = [np.asarray(t) for t in tensors]
    if get_world_size() == 1 or not arrs:
        return arrs
    per_rank = all_gather_object(arrs)  # the single rendezvous
    out = []
    for i, a in enumerate(arrs):
        stack = np.stack([np.asarray(r[i]) for r in per_rank])
        if op in (ReduceOp.SUM, ReduceOp.AVG):
            red = stack.sum(axis=0)
            if op == ReduceOp.AVG:
                red = red / get_world_size()
        elif op == ReduceOp.MAX:
            red = stack.max(axis=0)
        elif op == ReduceOp.MIN:
            red = stack.min(axis=0)
        elif op == ReduceOp.PRODUCT:
            red = stack.prod(axis=0)
        else:
            raise DSCommError(f"unsupported eager reduce op {op}")
        out.append(red.astype(a.dtype, copy=False))
    return out


def all_gather_coalesced(tensor_list, group=None, async_op: bool = False):  # noqa: ARG001
    """Coalesced all-gather: one rendezvous, per-rank lists back."""
    world = get_world_size()
    full = all_gather_object([np.asarray(t) for t in tensor_list])
    return [[full[r][i] for r in range(world)] for i in range(len(tensor_list))]


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False):  # noqa: ARG001
    """Reference TorchBackend.inference_all_reduce: same reduction, fast
    path hint only — on TPU the inference TP reduction is a GSPMD psum
    inside the jitted forward, so the control-plane form just reduces."""
    return all_reduce(tensor, op=op, group=group)


def monitored_barrier(group=None, timeout=None, wait_all_ranks: bool = False, name: str = "") -> None:  # noqa: ARG001
    """Barrier with slow-rank visibility (torch monitored_barrier). A
    watchdog thread logs WHILE the barrier is stuck (a post-hoc check could
    never fire on a genuine missing-rank hang); the barrier itself cannot be
    aborted, so like the rendezvous it rides, this surfaces the hang rather
    than raising past it."""
    import threading

    limit = timeout if timeout is not None else 300.0
    try:
        limit = float(getattr(limit, "total_seconds", lambda: limit)())
    except Exception:
        limit = 300.0
    done = threading.Event()

    def _watch():
        waited = 0.0
        while not done.wait(min(limit, 30.0)):
            waited += min(limit, 30.0)
            if waited >= limit:
                logger.warning(
                    f"monitored_barrier '{name}' still waiting after "
                    f"{waited:.0f}s (limit {limit:.0f}s) — a rank may be down"
                )

    if get_world_size() > 1:
        t = threading.Thread(target=_watch, daemon=True)
        t.start()
    t0 = time.time()
    try:
        barrier(group=group, name=name or "ds_monitored_barrier")
    finally:
        done.set()
    dt = time.time() - t0
    if dt > limit:
        logger.warning(f"monitored_barrier took {dt:.1f}s (limit {limit:.1f}s)")


# point-to-point (reference comm.py isend/irecv/send/recv). The training
# pipeline never uses host p2p — stage handoffs are ppermute INSIDE the
# compiled program (runtime/pipe/spmd.py) — so these exist for the control
# plane and API parity. The rendezvous primitives are collective, so p2p is
# cooperative: every p2p call is one exchange ROUND in which all ranks
# publish their pending outbound messages into per-(src,dst,tag) mailboxes;
# receives drain the mailbox first and only join further rounds while
# empty-handed. This makes the standard nonblocking orderings (both ranks
# isend then irecv) deliver correctly instead of pairing sends with sends.
_p2p_mailbox: dict = {}
_P2P_MAX_ROUNDS = 1000


def _p2p_round(outbound: list) -> None:
    for msgs in all_gather_object(outbound):
        for (s, d, t, payload) in msgs or []:
            _p2p_mailbox.setdefault((s, d, t), []).append(payload)


def send(tensor, dst: int, group=None, tag: int = 0) -> None:  # noqa: ARG001
    if get_world_size() == 1:
        _p2p_mailbox.setdefault((0, 0, tag), []).append(np.asarray(tensor))
        return
    _p2p_round([(get_rank(), dst, tag, np.asarray(tensor))])


def recv(tensor, src: int, group=None, tag: int = 0):  # noqa: ARG001
    key = (src, get_rank(), tag)
    if get_world_size() == 1:
        box = _p2p_mailbox.get(key)
        return box.pop(0) if box else None
    for _ in range(_P2P_MAX_ROUNDS):
        box = _p2p_mailbox.get(key)
        if box:
            return box.pop(0)
        _p2p_round([])
    raise DSCommError(
        f"recv(src={src}, tag={tag}) saw no matching send after "
        f"{_P2P_MAX_ROUNDS} exchange rounds"
    )


class _Work:
    """Completed-work handle (torch dist.Work parity for isend/irecv)."""

    def __init__(self, value=None):
        self.value = value

    def wait(self):
        return self.value

    def is_completed(self) -> bool:
        return True


def isend(tensor, dst: int, group=None, tag: int = 0) -> _Work:  # noqa: ARG001
    send(tensor, dst, group=group, tag=tag)
    return _Work()


def irecv(tensor, src: int, group=None, tag: int = 0) -> _Work:  # noqa: ARG001
    return _Work(recv(tensor, src, group=group, tag=tag))


# cloud environment detection + env patches (reference comm.py:726,758) ----
def in_aml() -> bool:
    return "AZUREML_EXPERIMENT_ID" in os.environ


def in_aws_sm() -> bool:
    return "SM_TRAINING_ENV" in os.environ


def in_dlts() -> bool:
    return "DLTS_JOB_ID" in os.environ


def patch_aml_env_for_torch_nccl_backend(master_port: int = 6105, verbose: bool = True) -> None:
    """AzureML: derive RANK/WORLD_SIZE/MASTER_* from the MPI envs AML sets
    (reference comm.py:726)."""
    # OVERWRITE (not setdefault): a stale RANK=0 exported on every node must
    # lose to the MPI-provided values or every process claims rank 0
    os.environ["RANK"] = os.environ.get("OMPI_COMM_WORLD_RANK", os.environ.get("RANK", "0"))
    os.environ["WORLD_SIZE"] = os.environ.get(
        "OMPI_COMM_WORLD_SIZE", os.environ.get("WORLD_SIZE", "1")
    )
    single_node = int(os.environ.get("OMPI_COMM_WORLD_LOCAL_SIZE", "1")) == int(
        os.environ.get("WORLD_SIZE", "1")
    )
    if not single_node:
        master_node_params = os.environ.get("AZ_BATCH_MASTER_NODE", ":").split(":")
        os.environ.setdefault("MASTER_ADDR", master_node_params[0])
        if len(master_node_params) > 1 and master_node_params[1]:
            os.environ.setdefault("MASTER_PORT", master_node_params[1])
    else:
        os.environ.setdefault("MASTER_ADDR", os.environ.get("AZ_BATCHAI_MPI_MASTER_NODE", "127.0.0.1"))
        os.environ.setdefault("MASTER_PORT", str(master_port))
    os.environ["LOCAL_RANK"] = os.environ.get(
        "OMPI_COMM_WORLD_LOCAL_RANK", os.environ.get("LOCAL_RANK", "0")
    )
    if verbose:
        logger.info(
            f"AML env: rank={os.environ['RANK']} world={os.environ['WORLD_SIZE']} "
            f"master={os.environ.get('MASTER_ADDR')}:{os.environ.get('MASTER_PORT')}"
        )


def patch_aws_sm_env_for_torch_nccl_backend(verbose: bool = True) -> None:
    """SageMaker: RANK/LOCAL_RANK from the SM MPI envs (reference comm.py:758)."""
    os.environ["RANK"] = os.environ.get("OMPI_COMM_WORLD_RANK", os.environ.get("RANK", "0"))
    os.environ["LOCAL_RANK"] = os.environ.get(
        "OMPI_COMM_WORLD_LOCAL_RANK", os.environ.get("LOCAL_RANK", "0")
    )
    os.environ["WORLD_SIZE"] = os.environ.get(
        "OMPI_COMM_WORLD_SIZE", os.environ.get("WORLD_SIZE", "1")
    )
    if verbose:
        logger.info(
            f"SageMaker env: rank={os.environ['RANK']} world={os.environ['WORLD_SIZE']}"
        )
