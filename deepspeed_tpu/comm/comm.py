"""Device-agnostic collectives facade.

TPU-native counterpart of the reference's ``deepspeed/comm/comm.py`` (the
module-level API mirroring torch.distributed: ``init_distributed`` comm.py:604,
``all_reduce`` :483, ``all_to_all_single`` :331, ``barrier``, profiling
decorator ``timed_op`` :101, ``log_summary`` :422).

Two planes exist on TPU:

* **Compute plane** (the hot path): collectives inside jitted programs are
  emitted by the GSPMD partitioner from sharding annotations, or written
  explicitly with ``jax.lax`` collectives under ``shard_map`` (see
  ``deepspeed_tpu.comm.collectives``). Nothing in this module runs there.
* **Control plane** (this module): process-level rendezvous
  (``jax.distributed.initialize``), eager cross-process reductions of small
  host values (loss averages, overflow flags, checkpoint tags), barriers and
  object broadcast. These ride DCN, exactly like the reference's Gloo/TCP
  store usage for control data.

Rank/world-size semantics: on TPU there is one process per *host* and the
devices hang off a mesh, so ``get_rank``/``get_world_size`` are process-level
(matching the launcher), while ``get_device_count``/``get_global_device_count``
expose chip counts for sharding math.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.comm.reduce_op import ReduceOp
from deepspeed_tpu.utils.comms_logging import CommsLogger, calc_bw_log
from deepspeed_tpu.utils.logging import logger

# module state -------------------------------------------------------------
cdb_initialized = False
comms_logger = CommsLogger()
_timers = {}


class DSCommError(RuntimeError):
    pass


def _jax():
    import jax

    return jax


# -- init ------------------------------------------------------------------
def init_distributed(
    dist_backend: str = "xla",
    auto_mpi_discovery: bool = True,  # noqa: ARG001 - kept for API parity
    distributed_port: int = 29500,
    verbose: bool = True,
    timeout=None,  # noqa: ARG001
    init_method: Optional[str] = None,  # noqa: ARG001
    dist_init_required: Optional[bool] = None,  # noqa: ARG001
    config=None,  # noqa: ARG001
    rank: int = -1,
    world_size: int = -1,
) -> None:
    """Initialize the process-level distributed runtime.

    Multi-host coordinates come from (in priority order) explicit args, the
    standard JAX cluster envs, or DeepSpeed-style ``MASTER_ADDR``/``RANK``/
    ``WORLD_SIZE`` envs set by the launcher. Single-process if none present.
    """
    global cdb_initialized
    if cdb_initialized:
        return
    if dist_backend not in ("xla", "nccl", "gloo", "ccl", "hccl"):
        raise DSCommError(f"unknown dist backend {dist_backend!r}")

    jax = _jax()
    coordinator = os.environ.get("COORDINATOR_ADDRESS")
    env_world = int(os.environ.get("WORLD_SIZE", world_size if world_size > 0 else 1))
    env_rank = int(os.environ.get("RANK", rank if rank >= 0 else 0))
    if coordinator is None and env_world > 1 and "MASTER_ADDR" in os.environ:
        coordinator = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', distributed_port)}"
    if coordinator is not None and env_world > 1:
        if verbose:
            logger.info(
                f"Initializing jax.distributed: coordinator={coordinator} "
                f"process={env_rank}/{env_world}"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env_world,
            process_id=env_rank,
        )
    elif verbose:
        logger.info("Single-process distributed runtime (no coordinator found)")
    cdb_initialized = True


def is_initialized() -> bool:
    return cdb_initialized


def destroy_process_group(group=None) -> None:  # noqa: ARG001
    global cdb_initialized
    try:
        _jax().distributed.shutdown()
    except Exception:
        pass
    cdb_initialized = False


# -- topology queries ------------------------------------------------------
def get_rank(group=None) -> int:  # noqa: ARG001
    try:
        return _jax().process_index()
    except Exception:
        return 0


def get_world_size(group=None) -> int:
    if group is not None and hasattr(group, "size"):
        return group.size
    try:
        return _jax().process_count()
    except Exception:
        return 1


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))


def get_device_count() -> int:
    return _jax().local_device_count()


def get_global_device_count() -> int:
    return _jax().device_count()


def get_all_ranks_from_group(group=None) -> List[int]:
    if group is not None and hasattr(group, "ranks"):
        return list(group.ranks)
    return list(range(get_world_size()))


# -- profiling decorator ---------------------------------------------------
def timed_op(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        prof = getattr(comms_logger, "prof_all", False) or func.__name__ in comms_logger.prof_ops
        if not prof:
            return func(*args, **kwargs)
        start = time.perf_counter()
        result = func(*args, **kwargs)
        if result is not None and hasattr(result, "block_until_ready"):
            result.block_until_ready()
        latency_ms = (time.perf_counter() - start) * 1000.0
        comms_logger.append(func.__name__, func.__name__, latency_ms, _nbytes(args))
        return result

    return wrapper


def _nbytes(args) -> int:
    """Payload size: the first array-like positional arg (skips output lists)."""
    for x in args:
        try:
            return int(x.size * x.dtype.itemsize)
        except Exception:
            continue
    return 0


def configure(config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None) -> None:
    if config is not None and hasattr(config, "comms_config"):
        comms_logger.configure(config.comms_config)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def log_summary(show_straggler: bool = False):
    return comms_logger.log_all(print_log=True, show_straggler=show_straggler)


# -- eager control-plane collectives ---------------------------------------
def _multihost():
    from jax.experimental import multihost_utils

    return multihost_utils


def barrier(group=None, name: str = "") -> None:  # noqa: ARG001
    if get_world_size() > 1:
        _multihost().sync_global_devices(name or "ds_barrier")


@timed_op
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group=None, async_op: bool = False):  # noqa: ARG001
    """Eager cross-process reduction of a host/global value; returns the result.

    JAX arrays are immutable so this returns rather than mutating in place;
    engine call-sites assign the result back.
    """
    arr = np.asarray(tensor)
    if get_world_size() == 1:
        return arr
    gathered = _multihost().process_allgather(arr)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = gathered.sum(axis=0)
        if op == ReduceOp.AVG:
            out = out / get_world_size()
    elif op == ReduceOp.MAX:
        out = gathered.max(axis=0)
    elif op == ReduceOp.MIN:
        out = gathered.min(axis=0)
    elif op == ReduceOp.PRODUCT:
        out = gathered.prod(axis=0)
    else:
        raise DSCommError(f"unsupported eager reduce op {op}")
    return out


@timed_op
def all_gather(tensor_list: Optional[list], tensor, group=None, async_op: bool = False):  # noqa: ARG001
    arr = np.asarray(tensor)
    if get_world_size() == 1:
        gathered = arr[None]
    else:
        gathered = _multihost().process_allgather(arr)
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(list(gathered))
    return gathered


@timed_op
def broadcast(tensor, src: int = 0, group=None, async_op: bool = False):  # noqa: ARG001
    if get_world_size() == 1:
        return np.asarray(tensor)
    return _multihost().broadcast_one_to_all(np.asarray(tensor), is_source=get_rank() == src)


def broadcast_object_list(object_list: list, src: int = 0, group=None) -> None:  # noqa: ARG001
    import pickle

    if get_world_size() == 1:
        return
    payload = pickle.dumps(object_list) if get_rank() == src else b""
    # length-prefix exchange, then payload broadcast
    length = int(broadcast(np.array([len(payload)], dtype=np.int64), src=src)[0])
    buf = np.zeros(length, dtype=np.uint8)
    if get_rank() == src:
        buf[:] = np.frombuffer(payload, dtype=np.uint8)
    out = _multihost().broadcast_one_to_all(buf, is_source=get_rank() == src)
    object_list[:] = pickle.loads(out.tobytes())


def all_gather_object(obj: Any) -> List[Any]:
    import pickle

    if get_world_size() == 1:
        return [obj]
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    lengths = all_reduce(
        np.eye(get_world_size(), dtype=np.int64)[get_rank()] * len(payload), op=ReduceOp.SUM
    )
    maxlen = int(lengths.max())
    padded = np.zeros(maxlen, dtype=np.uint8)
    padded[: len(payload)] = payload
    gathered = _multihost().process_allgather(padded)
    return [pickle.loads(gathered[i, : int(lengths[i])].tobytes()) for i in range(get_world_size())]


# torch.distributed capability probes mirrored for API parity --------------
def has_all_gather_into_tensor() -> bool:
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def has_coalescing_manager() -> bool:
    # GSPMD fuses collectives; a coalescing manager is implicit.
    return True


def get_global_rank(group=None, group_rank: int = 0) -> int:  # noqa: ARG001
    return group_rank


def new_group(ranks: Sequence[int]):
    """Process groups are mesh axes on TPU; return a lightweight handle."""

    class _Group:
        def __init__(self, ranks):
            self.ranks = list(ranks)
            self.size = len(self.ranks)

    return _Group(ranks)


# MPI / cloud env discovery (reference comm.py:671,726,758) -----------------
def mpi_discovery(distributed_port: int = 29500, verbose: bool = True) -> None:
    """Populate RANK/WORLD_SIZE/MASTER_* from OpenMPI envs when present."""
    ompi_rank = os.environ.get("OMPI_COMM_WORLD_RANK")
    if ompi_rank is None:
        return
    os.environ.setdefault("RANK", ompi_rank)
    os.environ.setdefault("WORLD_SIZE", os.environ.get("OMPI_COMM_WORLD_SIZE", "1"))
    os.environ.setdefault("LOCAL_RANK", os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", "0"))
    os.environ.setdefault("MASTER_PORT", str(distributed_port))
    if verbose:
        logger.info(
            f"MPI discovery: rank={os.environ['RANK']} world={os.environ['WORLD_SIZE']}"
        )
