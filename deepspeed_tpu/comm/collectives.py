"""In-mesh collectives for the compute plane.

These are the TPU-native equivalents of the reference's NCCL collective calls
(``deepspeed/comm/torch.py``) and of the ZeRO++ quantized collectives
(``deepspeed/runtime/comm/coalesced_collectives.py:31`` —
``all_to_all_quant_reduce``, ``reduce_scatter_coalesced``). They are meant to
be used *inside* ``shard_map``-decorated functions over a named mesh axis,
where they lower to ICI collectives.

The coalesced variants take pytrees: a single flattened collective per dtype
replaces the reference's coalescing manager.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis_name: str):
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    return lax.pmean(x, axis_name)

def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, tiled: bool = True):
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name: str, perm: Sequence[tuple]):
    return lax.ppermute(x, axis_name, perm=perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Shift shards around the ring defined by ``axis_name`` (for ring attention)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


# --- quantized collectives (ZeRO++ analog) --------------------------------
def _int8_quantize(x: jax.Array, block: int = 2048):
    """Symmetric per-block int8 quantization (jnp path; Pallas kernel in ops/)."""
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32), x.shape, pad


def _int8_dequantize(q, scale, shape, pad):
    out = (q.astype(jnp.float32) * scale).reshape(-1)
    if pad:
        out = out[: out.size - pad]
    return out.reshape(shape)


def quantized_reduce_scatter(x, axis_name: str, n_shards: int, block: int = 1024):
    """Reduce-scatter with int8-quantized payload.

    TPU-native analog of ``all_to_all_quant_reduce`` (coalesced_collectives.py:31):
    per-block quantize → all_to_all → dequantize → local reduce. Quarters (vs
    fp32) the bytes on the wire at the cost of one quantization error; used for
    ZeRO++-style gradient reduction. ``n_shards`` must equal the size of the
    mesh axis (static, since shapes inside jit are static). Returns the
    caller's reduced shard of length ``ceil(x.size / n)`` (padded with zeros).
    """
    n = n_shards
    flat = x.reshape(-1)
    if flat.size == 0:
        return flat
    flat = jnp.pad(flat, (0, (-flat.size) % n))
    L = flat.size // n
    blk = min(block, L)
    pad_b = (-L) % blk
    shards = jnp.pad(flat.reshape(n, L), ((0, 0), (0, pad_b)))  # [n, Lp]
    blocks = shards.reshape(n, -1, blk)
    scale = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    # exchange destination-shard rows: row j ends up holding every rank's
    # contribution to shard j
    q = lax.all_to_all(q.reshape(n, -1), axis_name, split_axis=0, concat_axis=0, tiled=True)
    scale = lax.all_to_all(scale.reshape(n, -1), axis_name, split_axis=0, concat_axis=0, tiled=True)
    deq = q.reshape(n, -1, blk).astype(jnp.float32) * scale.reshape(n, -1, 1)
    out = deq.sum(axis=0).reshape(-1)
    return out[:L]


def quantized_all_gather(x, axis_name: str, block: int = 2048):
    """All-gather with int8-quantized payload (ZeRO++ qwZ analog)."""
    q, scale, shape, pad = _int8_quantize(x, block)
    qg = lax.all_gather(q, axis_name, axis=0, tiled=False)
    sg = lax.all_gather(scale, axis_name, axis=0, tiled=False)
    n = qg.shape[0]
    return jax.vmap(lambda qq, ss: _int8_dequantize(qq, ss, shape, pad))(qg, sg)
