"""Config model base utilities.

Counterpart of the reference's ``deepspeed/runtime/config_utils.py:16-139``:
``DeepSpeedConfigModel`` (a pydantic base that tolerates the literal string
``"auto"`` for any field and implements deprecated-field remapping), ``pp_int``
pretty-printed ints, and a scientific-notation-friendly JSON encoder.
"""

from __future__ import annotations

import json
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, model_validator

AUTO = "auto"


class DeepSpeedConfigModel(BaseModel):
    """Base for all config sections.

    Fields set to the literal ``"auto"`` are stripped before validation and
    fall back to their defaults, while ``is_auto(name)`` reports which fields
    the user left as auto (the HF-Trainer integration contract). Deprecated
    fields declare ``json_schema_extra={"deprecated": True, "new_param": ...}``
    and are copied onto their replacement at validation time.
    """

    model_config = ConfigDict(
        validate_assignment=True,
        populate_by_name=True,
        extra="forbid",
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, strict: bool = False, **data):
        if not strict:
            auto_fields = {k for k, v in data.items() if v == AUTO}
            data = {k: v for k, v in data.items() if v != AUTO}
        else:
            auto_fields = set()
        super().__init__(**data)
        object.__setattr__(self, "_auto_fields", auto_fields)

    def is_auto(self, field_name: str) -> bool:
        return field_name in getattr(self, "_auto_fields", set())

    @model_validator(mode="before")
    @classmethod
    def _remap_deprecated(cls, values: Any) -> Any:
        if not isinstance(values, dict):
            return values
        for name, field in cls.model_fields.items():
            extra = field.json_schema_extra or {}
            if not isinstance(extra, dict) or not extra.get("deprecated"):
                continue
            if name in values and values[name] is not None:
                new_param = extra.get("new_param")
                if new_param and new_param not in values:
                    values[new_param] = values[name]
        return values

    def dict_repr(self) -> Dict[str, Any]:
        return self.model_dump()


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """JSON object-pairs hook that rejects duplicate keys (reference config.py)."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        dupes = [k for k, c in counter.items() if c > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {dupes}")
    return d


class pp_int(int):
    """Int that remembers a human-readable form for config dumps (config_utils.py:120)."""

    def __new__(cls, val: int, custom_print_str: str = None):
        inst = super().__new__(cls, val)
        inst.custom_print_str = custom_print_str
        return inst

    def __repr__(self):
        if self.custom_print_str:
            return self.custom_print_str
        return f"{int(self):_}"


class ScientificNotationEncoder(json.JSONEncoder):
    """JSON encoder emitting large numbers in scientific notation (config_utils.py:139)."""

    def iterencode(self, o, _one_shot=False):
        if isinstance(o, (int, float)) and not isinstance(o, bool) and abs(o) >= 1e4:
            return iter([f"{o:e}"])
        if isinstance(o, dict):
            parts = [f'"{k}": {"".join(self.iterencode(v))}' for k, v in o.items()]
            return iter(["{" + ", ".join(parts) + "}"])
        if isinstance(o, (list, tuple)):
            return iter(["[" + ", ".join("".join(self.iterencode(v)) for v in o) + "]"])
        return super().iterencode(o, _one_shot=_one_shot)
