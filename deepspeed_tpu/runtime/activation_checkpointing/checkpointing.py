"""Activation checkpointing.

Counterpart of the reference's Megatron-derived checkpointing
(``deepspeed/runtime/activation_checkpointing/checkpointing.py``:
``CheckpointFunction`` :475, ``configure`` :1055, partitioned/CPU/contiguous
activation options). On TPU the mechanism is ``jax.checkpoint`` (remat):
instead of saving activations and replaying autograd, XLA recomputes the
wrapped region in the backward pass, with a *policy* choosing what to keep.

Config translation (JSON keys are the reference's, ``configure`` semantics):

* ``partition_activations``  → policy keeps nothing across the region and
  the saved residuals are sharded by GSPMD anyway (sharded-by-construction —
  the reference's cross-mp-rank partitioning is what PartitionSpecs already
  do to the saved tensors);
* ``cpu_checkpointing``      → ``jax.checkpoint`` with offload policy
  (``save_and_offload_only_these_names`` host offload when available);
* ``contiguous_memory_optimization`` / ``number_checkpoints`` → no-ops
  (XLA's allocator packs remat buffers);
* ``synchronize_checkpoint_boundary`` → no-op (no streams to sync).

``checkpoint(fn, *args)`` matches the reference's call surface
(checkpointing.py:954) and the RNG plumbing is jax-native: pass rngs
explicitly — deterministic replay is automatic because jax PRNG keys are
values, which is what the reference's ``CudaRNGStatesTracker`` (:122)
reconstructs by hand.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from deepspeed_tpu.utils.logging import logger

_config: dict = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
}
_configured = False

# policy table: reference knob combinations → jax.checkpoint policies
_POLICIES = {
    "default": None,  # save nothing; recompute everything (max memory saving)
    "dots": "checkpoint_dots",
    "dots_no_batch": "checkpoint_dots_with_no_batch_dims",
    "nothing": "nothing_saveable",
    "everything": "everything_saveable",
}


def configure(
    mpu_=None,  # noqa: ARG001 - reference parity (mesh already global)
    deepspeed_config=None,
    partition_activations: Optional[bool] = None,
    contiguous_checkpointing: Optional[bool] = None,
    num_checkpoints: Optional[int] = None,
    checkpoint_in_cpu: Optional[bool] = None,
    synchronize: Optional[bool] = None,
    profile: Optional[bool] = None,
) -> None:
    """(reference :1055) — accepts both the config object and kwargs."""
    global _configured
    cfg = None
    if deepspeed_config is not None:
        cfg = getattr(deepspeed_config, "activation_checkpointing_config", None)
    if cfg is not None:
        _config["partition_activations"] = cfg.partition_activations
        _config["cpu_checkpointing"] = cfg.cpu_checkpointing
        _config["contiguous_memory_optimization"] = cfg.contiguous_memory_optimization
        _config["number_checkpoints"] = cfg.number_checkpoints
        _config["synchronize_checkpoint_boundary"] = cfg.synchronize_checkpoint_boundary
        _config["profile"] = cfg.profile
    for key, val in [
        ("partition_activations", partition_activations),
        ("contiguous_memory_optimization", contiguous_checkpointing),
        ("number_checkpoints", num_checkpoints),
        ("cpu_checkpointing", checkpoint_in_cpu),
        ("synchronize_checkpoint_boundary", synchronize),
        ("profile", profile),
    ]:
        if val is not None:
            _config[key] = val
    _configured = True
    logger.info(f"activation checkpointing configured: {_config}")


def is_configured() -> bool:
    return _configured


def get_partition_activations() -> bool:
    return _config["partition_activations"]


def policy_from_name(name: Optional[str]):
    """Resolve a policy knob to a jax.checkpoint policy callable."""
    if name is None or name == "default":
        return None
    attr = _POLICIES.get(name, name)
    if attr is None:
        return None
    pol = getattr(jax.checkpoint_policies, attr, None)
    if pol is None:
        logger.warning(f"unknown remat policy {name!r}; saving nothing")
    return pol


def _configured_policy():
    """Policy implied by configure()'s knobs when no explicit policy is
    given: cpu_checkpointing → host-offload the saved residuals (when this
    jax exposes an offload policy); otherwise save nothing (max remat)."""
    if _config["cpu_checkpointing"]:
        offload = getattr(jax.checkpoint_policies, "offload_dot_products_to_host", None)
        if offload is None:
            offload = getattr(jax.checkpoint_policies, "save_and_offload_only_these_names", None)
            offload = None if offload is None else None  # name-based: needs user names
        if offload is not None:
            return offload
        logger.warning(
            "cpu_checkpointing requested but this jax has no host-offload remat "
            "policy; falling back to full recomputation (nothing saved)"
        )
    return None


def checkpoint(function: Callable, *args, policy: Optional[str] = None, **kwargs) -> Any:
    """Rematerialized call (reference ``checkpoint`` :954): activations
    inside ``function`` are recomputed during backward instead of stored.
    With no explicit ``policy``, configure()'s knobs choose one."""
    pol = policy_from_name(policy) if policy is not None else _configured_policy()
    wrapped = jax.checkpoint(function, policy=pol, prevent_cse=False)
    return wrapped(*args, **kwargs)


def checkpoint_wrapper(function: Callable, policy: Optional[str] = None) -> Callable:
    """Decorator form: returns a remat'd version of ``function``."""
    pol = policy_from_name(policy) if policy is not None else _configured_policy()
    return jax.checkpoint(function, policy=pol, prevent_cse=False)


class CheckpointFunction:
    """API-parity shim for the reference's autograd.Function (:475): calling
    ``CheckpointFunction.apply(run_fn, *args)`` remats ``run_fn``."""

    @staticmethod
    def apply(run_function: Callable, *args) -> Any:
        return checkpoint(run_function, *args)


def model_parallel_cuda_manual_seed(seed: int) -> None:  # noqa: ARG001
    """No-op parity shim (reference :320): jax PRNG keys are explicit values,
    so there is no global RNG state to fork per mp rank."""


def reset() -> None:
    """Reset between configs (reference ``reset`` :1040)."""
    global _configured
    _configured = False
