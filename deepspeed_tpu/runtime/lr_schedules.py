"""LR schedules.

Counterpart of ``deepspeed/runtime/lr_schedules.py`` (763 LoC): LRRangeTest,
OneCycle, WarmupLR, WarmupDecayLR, WarmupCosineLR + the ``add_tuning_arguments``
CLI surface. Schedulers mutate ``optimizer.param_groups[i]['lr']`` exactly like
the reference; the engine feeds the current lr into the jitted step as a traced
scalar, so stepping the schedule never recompiles.
"""

from __future__ import annotations

import argparse
import math
from typing import List, Optional, Union

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
WARMUP_TYPE = "warmup_type"
WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"
TOTAL_NUM_STEPS = "total_num_steps"


def add_tuning_arguments(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    group = parser.add_argument_group("Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None, help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=1)
    group.add_argument("--cycle_second_step_size", type=int, default=None)
    group.add_argument("--cycle_second_stair_count", type=int, default=None)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default=WARMUP_LOG_RATE)
    return parser


class _LRSchedulerBase:
    def __init__(self, optimizer, last_batch_iteration: int = -1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def _update_lrs(self, lrs: List[float]) -> None:
        for group, lr in zip(self.optimizer.param_groups, lrs):
            group["lr"] = lr
        self._last_lr = lrs

    def get_lr(self) -> List[float]:
        raise NotImplementedError

    def get_last_lr(self) -> List[float]:
        assert getattr(self, "_last_lr", None) is not None, "need to call step() first"
        return self._last_lr

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._update_lrs(self.get_lr())

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
        # re-apply the restored schedule to optimizer.param_groups: an
        # uninterrupted run's step() already wrote this lr after the last
        # pre-save step, so a resumed run must start from the same value —
        # without this the first post-resume update silently consumes the
        # fresh-engine init lr (exact-resume parity catches it as a loss
        # divergence on the SECOND resumed step)
        if self.last_batch_iteration >= 0:
            self._update_lrs(self.get_lr())


class LRRangeTest(_LRSchedulerBase):
    """Linearly/staircase-growing lr for range tests (Smith 2017)."""

    def __init__(
        self,
        optimizer,
        lr_range_test_min_lr: float = 1e-3,
        lr_range_test_step_size: int = 2000,
        lr_range_test_step_rate: float = 1.0,
        lr_range_test_staircase: bool = False,
        last_batch_iteration: int = -1,
    ):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        if last_batch_iteration == -1:
            self._update_lrs([self.min_lr] * len(optimizer.param_groups))

    def get_lr(self) -> List[float]:
        count = self.last_batch_iteration / self.step_size
        if self.staircase:
            count = math.floor(count)
        return [self.min_lr * (1 + count * self.step_rate)] * len(self.optimizer.param_groups)


class OneCycle(_LRSchedulerBase):
    """1-cycle lr (and momentum) policy."""

    def __init__(
        self,
        optimizer,
        cycle_min_lr: float,
        cycle_max_lr: float,
        decay_lr_rate: float = 0.0,
        cycle_first_step_size: int = 2000,
        cycle_second_step_size: Optional[int] = None,
        cycle_first_stair_count: int = 0,
        cycle_second_stair_count: Optional[int] = None,
        decay_step_size: int = 0,
        cycle_momentum: bool = True,
        cycle_min_mom: float = 0.8,
        cycle_max_mom: float = 0.9,
        decay_mom_rate: float = 0.0,
        last_batch_iteration: int = -1,
    ):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = cycle_first_step_size
        self.second_size = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.total_size = self.first_size + self.second_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def get_lr(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        if it <= self.total_size:
            if it <= self.first_size:
                scale = it / self.first_size
            else:
                scale = 1.0 - (it - self.first_size) / self.second_size
            lr = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * scale
        else:
            decay_steps = (it - self.total_size) / max(self.decay_step_size, 1)
            lr = self.cycle_min_lr / (1.0 + self.decay_lr_rate * decay_steps)
        return [lr] * len(self.optimizer.param_groups)


class WarmupLR(_LRSchedulerBase):
    """Warmup from min to max lr, then hold (reference WarmupLR)."""

    def __init__(
        self,
        optimizer,
        warmup_min_lr: float = 0.0,
        warmup_max_lr: float = 0.001,
        warmup_num_steps: int = 1000,
        warmup_type: str = WARMUP_LOG_RATE,
        last_batch_iteration: int = -1,
    ):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _warmup_scale(self, it: int) -> float:
        if self.warmup_type == WARMUP_LOG_RATE:
            return self.inverse_log_warm_up * math.log(it + 1)
        return it / self.warmup_num_steps

    def get_lr(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        if it < self.warmup_num_steps:
            scale = self._warmup_scale(it)
            lr = self.min_lr + (self.max_lr - self.min_lr) * scale
        else:
            lr = self._post_warmup_lr(it)
        return [lr] * len(self.optimizer.param_groups)

    def _post_warmup_lr(self, it: int) -> float:  # noqa: ARG002
        return self.max_lr


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 over total_num_steps."""

    def __init__(
        self,
        optimizer,
        total_num_steps: int,
        warmup_min_lr: float = 0.0,
        warmup_max_lr: float = 0.001,
        warmup_num_steps: int = 1000,
        warmup_type: str = WARMUP_LOG_RATE,
        last_batch_iteration: int = -1,
    ):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type, last_batch_iteration)

    def _post_warmup_lr(self, it: int) -> float:
        frac = (self.total_num_steps - it) / max(self.total_num_steps - self.warmup_num_steps, 1)
        return self.max_lr * max(0.0, frac)


class WarmupCosineLR(WarmupLR):
    """Warmup then cosine decay to cos_min_ratio."""

    def __init__(
        self,
        optimizer,
        total_num_steps: int,
        warmup_min_ratio: float = 0.0,
        warmup_num_steps: int = 1000,
        cos_min_ratio: float = 1e-4,
        warmup_type: str = WARMUP_LINEAR_RATE,
        last_batch_iteration: int = -1,
    ):
        self.total_num_steps = total_num_steps
        self.cos_min_ratio = cos_min_ratio
        base_lr = optimizer.param_groups[0]["lr"]
        super().__init__(
            optimizer,
            warmup_min_lr=base_lr * warmup_min_ratio,
            warmup_max_lr=base_lr,
            warmup_num_steps=warmup_num_steps,
            warmup_type=warmup_type,
            last_batch_iteration=last_batch_iteration,
        )

    def _post_warmup_lr(self, it: int) -> float:
        progress = (it - self.warmup_num_steps) / max(self.total_num_steps - self.warmup_num_steps, 1)
        progress = min(max(progress, 0.0), 1.0)
        cosine = 0.5 * (1 + math.cos(math.pi * progress))
        return self.max_lr * (self.cos_min_ratio + (1 - self.cos_min_ratio) * cosine)


SCHEDULER_REGISTRY = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
    WARMUP_COSINE_LR: WarmupCosineLR,
}


def get_lr_scheduler(name: str, optimizer, **params):
    if name not in SCHEDULER_REGISTRY:
        raise ValueError(f"unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULER_REGISTRY[name](optimizer, **params)
