"""Progressive layer dropping (reference:
``deepspeed/runtime/progressive_layer_drop.py:40``, engine.py:1773).

PLD's keep-probability schedule theta(t) = (1-theta)·exp(-gamma·t) + theta;
the model consumes it as the per-layer survival probability (stochastic
depth). The engine exposes ``get_state()`` exactly like the reference so
model code reads ``pld_theta`` each step.
"""

from __future__ import annotations

import math


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> None:
        def _prob(x, gamma, p):
            return (1.0 - p) * math.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
