"""Training engine.

TPU-native counterpart of the reference's ``DeepSpeedEngine``
(``deepspeed/runtime/engine.py:174``). The public surface is preserved —
``forward`` (engine.py:1740), ``backward`` (:1881), ``step`` (:2079),
``save_checkpoint``/``load_checkpoint`` (:2961/:2638), gradient-accumulation
boundary bookkeeping — but the internals are functional: all state lives in
sharded jax.Arrays, and three jitted programs implement the hot loop:

* ``_fwd_bwd``   — loss + grads + accumulate (forward & backward fused; the
  reference's per-param grad hooks + bucketing, stage_1_and_2.py:858-1000,
  become XLA-scheduled reduce-scatters emitted from grad out-shardings).
* ``_step_fn``   — unscale, global-norm clip, overflow check, fused optimizer
  update on the master shards, bf16 re-cast + all-gather (= stage step
  :1705/stage3 :1880), loss-scale update — all inside one program, so an
  overflow skip costs a ``where``, not a host sync.
* ``_eval_fwd``  — forward only.

Three fused flavors collapse host work into ONE dispatch: at gas=1 the
optimizer update fuses into the forward program (``_jit_fused_step``); with
``compile.fuse_grad_accum`` on, gas>1 steps run as a ``lax.scan`` over
stacked microbatches plus the update (``_jit_fused_accum_step``, engaged
through ``train_batch``); and with ``compile.multi_step`` armed, N whole
optimizer steps fuse into one program (``_jit_fused_window_step`` — the
state tuple threads the scan carry, per-step lr values ride in as an array,
and the per-step losses drain asynchronously one window deferred), so every
per-step host cost amortizes to 1/N. All step-flavor
programs donate the full state tuple (params, master, opt_state, grad_acc,
scale_state) so XLA updates state in place instead of double-buffering it,
and every program is wrapped in compile telemetry
(``profiling/compile_telemetry.py``; ``engine.compile_stats()``).

ZeRO stages select the sharding trees (see ``runtime/zero/partition.py``);
nothing else changes between stages — that is the point of doing ZeRO on the
GSPMD partitioner instead of hooks.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu import comm as dist
from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.ops.adagrad.cpu_adagrad import DeepSpeedCPUAdagrad
from deepspeed_tpu.ops.adam.fused_adam import Adam, AdamState, AdamW, FusedAdam
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.ops.optimizer import DSOptimizer
from deepspeed_tpu.ops.sgd import SGD
from deepspeed_tpu.parallel.mesh import Topology, get_topology, initialize_topology
from deepspeed_tpu.profiling.compile_telemetry import (
    CompileTelemetry,
    configure_persistent_cache,
)
from deepspeed_tpu.profiling.tracer import MetricsRegistry, ObservabilityHub, Tracer
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.checkpoint_engine.atomic import (
    CheckpointCorruptError,
    CheckpointLoadError,
    list_valid_tags,
    write_latest_marker,
)
from deepspeed_tpu.runtime.checkpoint_engine.async_snapshot import (
    AsyncCheckpointWriter,
    host_snapshot,
    tree_fully_addressable,
)
from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import OrbaxCheckpointEngine
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    CreateLossScaler,
    LossScaleState,
    has_inf_or_nan,
)
from deepspeed_tpu.runtime.lr_schedules import get_lr_scheduler
from deepspeed_tpu.runtime.module import DSModule, wrap_module
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
from deepspeed_tpu.utils import chaos
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (
    BACKWARD_GLOBAL_TIMER,
    FORWARD_GLOBAL_TIMER,
    STEP_GLOBAL_TIMER,
    NoopTimer,
    SynchronizedWallClockTimer,
    ThroughputTimer,
)

MEMORY_OPT_ALLREDUCE_SIZE = 500_000_000  # parity: engine.py:105

# sentinel: a multi-step window could not form this step (the caller falls
# back to the bit-identical single-step path)
_NO_WINDOW = object()


def _enqueue_host_copies(leaves) -> None:
    """Start device→host copies on every array that supports it (the async
    half of the deferred loss drain): a later ``device_get`` completes an
    in-flight copy instead of starting a blocking one."""
    for leaf in leaves:
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()

from deepspeed_tpu.runtime.fp16.onebit import OnebitAdam, OnebitLamb, ZeroOneAdam

_OPTIMIZER_REGISTRY = {
    C.ONEBIT_ADAM_OPTIMIZER: OnebitAdam,
    C.ONEBIT_LAMB_OPTIMIZER: OnebitLamb,
    C.ZERO_ONE_ADAM_OPTIMIZER: ZeroOneAdam,
    # reference parity: "adam" selects FusedAdam whose adam_w_mode defaults
    # True (decoupled decay), engine.py:1233 + ops/adam/fused_adam.py
    C.ADAM_OPTIMIZER: FusedAdam,
    C.ADAMW_OPTIMIZER: AdamW,
    C.FUSED_ADAM_OPTIMIZER: FusedAdam,
    C.CPU_ADAM_OPTIMIZER: FusedAdam,  # host-offload variant selected via zero config
    C.CPU_ADAGRAD_OPTIMIZER: DeepSpeedCPUAdagrad,
    C.ADAGRAD_OPTIMIZER: DeepSpeedCPUAdagrad,
    C.LAMB_OPTIMIZER: FusedLamb,
    C.FUSED_LAMB_OPTIMIZER: FusedLamb,
    C.SGD_OPTIMIZER: SGD,
}


class DeepSpeedEngine:
    _is_pipe_engine = False
    def __init__(
        self,
        args=None,
        model=None,
        optimizer: Optional[DSOptimizer] = None,
        model_parameters: Any = None,
        training_data=None,
        lr_scheduler=None,
        mpu=None,
        dist_init_required: Optional[bool] = None,  # noqa: ARG002
        collate_fn: Optional[Callable] = None,
        config: Any = None,
        config_class: Optional[DeepSpeedConfig] = None,
        loss_fn: Optional[Callable] = None,
        dont_change_device: bool = False,  # noqa: ARG002
    ):
        self.args = args
        self.module: DSModule = wrap_module(model, loss_fn=loss_fn)
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu

        self._config = config_class or DeepSpeedConfig(config if config is not None else {}, mpu)
        # Sparse embedding gradients (reference engine.py:2398: embedding
        # grads reduced as compact (ids, rows) pairs). The model family's
        # lookup switches to ``sparse_embedding_lookup``
        # (runtime/sparse_tensor.py) whose custom VJP all-gathers pairs
        # inside a shard_map — requires ZeRO ≤ 1 (a stage-2/3 grad
        # reduce-scatter would re-shard the dense table grad, defeating the
        # compact reduction; the reference's sparse paths are stage-1-only
        # too). The gate guards the MECHANISM: it fires whether the request
        # came from the JSON key or from a model config built with
        # ``sparse_embedding_grads=True`` directly.
        mcfg = getattr(self.module, "config", None)
        model_flag = bool(getattr(mcfg, "sparse_embedding_grads", False))
        if self._config.sparse_gradients_enabled or model_flag:
            if int(self._config.zero_optimization_stage) > 1:
                raise ValueError(
                    "sparse_gradients requires ZeRO stage <= 1 (the compact "
                    "pair reduction replaces the dense grad reduce-scatter)"
                )
        if self._config.sparse_gradients_enabled and not model_flag:
            if mcfg is not None and hasattr(mcfg, "sparse_embedding_grads"):
                if getattr(mcfg, "tie_embeddings", False):
                    raise ValueError(
                        "sparse_gradients requires an untied embedding table "
                        "(set tie_embeddings=False): a tied LM head makes the "
                        "table gradient dense"
                    )
                # wire the engine-level key into the family switch (documented
                # side effect — the reference's engine likewise rewrites how
                # embedding grads are produced when the key is set)
                mcfg.sparse_embedding_grads = True
            elif not getattr(self.module, "supports_sparse_gradients", False):
                raise NotImplementedError(
                    "sparse_gradients: this module family has no sparse "
                    "embedding switch (TransformerLM exposes "
                    "config.sparse_embedding_grads); remove the key or use a "
                    "family that supports it"
                )
        self._apply_mics_mesh()
        self._validate_zeropp_config()
        self._grad_accum_dtype()  # validate combos up front, every path
        # a GROUPS-established topology (utils.groups.initialize before
        # deepspeed.initialize — the reference's pre-created process groups)
        # wins when this config doesn't ask for a specific mesh. Leftover
        # topologies from unrelated engines are NOT adopted: a default-mesh
        # training run must not inherit, say, an inference TP mesh.
        live = _live_topology()
        adopt = _topology_matches(self._config) or (
            not _config_requests_mesh(self._config)
            and live is not None
            and getattr(live, "user_established", False)
        )
        self.topology: Topology = get_topology() if adopt else initialize_topology(
            self._config.mesh_config
        )
        self.mesh = self.topology.mesh
        self._config.resolve_batch_triad(self.topology.get_data_parallel_world_size())

        dist.configure(self._config)

        # precision ------------------------------------------------------
        if self._config.bfloat16_enabled:
            self.compute_dtype = jnp.bfloat16
        elif self._config.fp16_enabled:
            self.compute_dtype = jnp.float16
        else:
            self.compute_dtype = jnp.float32
        self.mixed_precision = self.compute_dtype != jnp.float32
        self.dynamic_loss_scale = self._config.fp16_enabled and self._config.loss_scale == 0
        self.loss_scaler = CreateLossScaler(
            self.compute_dtype,
            self._config.loss_scale,
            self.dynamic_loss_scale,
            self._config.dynamic_loss_scale_args,
        )

        # optimizer ------------------------------------------------------
        self.optimizer = self._configure_optimizer()
        self.lr_scheduler = self._configure_lr_scheduler()

        # grad divisor at step time: normally the GAS count (each micro-step
        # accumulated one microbatch's grads); the pipeline engine fuses all
        # microbatches into one fwd_bwd whose loss is already the mean, so it
        # overrides this to 1 before the jitted fns are built.
        self._gas_divisor = self.gradient_accumulation_steps()

        # counters -------------------------------------------------------
        self.micro_steps = 0
        self.global_steps = 0
        self.global_samples = 0
        self.skipped_steps = 0
        self._in_forward = False
        self._training_mode = True

        # unified tracing & metrics plane (profiling/tracer.py) -----------
        # host-side spans around every step-loop phase + a metrics registry,
        # merged with the compile/analysis/checkpoint surfaces by
        # observability(). Tracing is pure host bookkeeping: zero device
        # transfers, zero compiled programs (guarded by tests).
        tcfg = self._config.tracing_config
        self.tracer = Tracer(max_spans=tcfg.max_spans, enabled=tcfg.enabled)
        self.metrics = MetricsRegistry()
        self._obs_hub = ObservabilityHub(self.tracer, self.metrics)
        self._obs_hub.add_source("compile", self.compile_stats)
        self._obs_hub.add_source("analysis", self.analysis_report)
        self._obs_hub.add_source("checkpoint", self.checkpoint_stats)
        # enforce=False: an over-budget ledger must surface IN the snapshot,
        # not blow up the whole observability read
        self._obs_hub.add_source(
            "memory", lambda: self.memory_report(enforce=False)
        )
        if tcfg.flight_recorder:
            self._obs_hub.install_flight_recorder(
                dump_dir=tcfg.flight_recorder_dir,
                last_spans=tcfg.flight_recorder_spans,
            )
        dist.set_comm_tracer(self.tracer)

        # timers ---------------------------------------------------------
        self.wall_clock_breakdown = self._config.wall_clock_breakdown
        self.timers = (
            SynchronizedWallClockTimer(tracer=self.tracer)
            if self.wall_clock_breakdown
            else NoopTimer()
        )
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self._config.steps_per_print,
            logging_fn=lambda msg: log_dist(msg, ranks=[0]),
        )

        # curriculum learning (reference engine.py:1779-1782 seqlen kwarg;
        # here: per-step truncation of the batch's sequence dim) ----------
        self.curriculum_scheduler = None
        cl_cfg = self._config.curriculum_learning_config
        if cl_cfg and cl_cfg.get("enabled", False):
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
                CurriculumScheduler,
            )

            self.curriculum_scheduler = CurriculumScheduler(cl_cfg)

        # progressive layer drop (reference engine.py:1773 pld_theta kwarg;
        # here: a traced scalar through model_kwargs — stochastic depth with
        # a lax.cond skip inside the layer loop) --------------------------
        self.progressive_layer_drop = None
        if self._config.pld_config.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop

            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self._config.pld_config.theta,
                gamma=self._config.pld_config.gamma,
            )

        # random-LTD (reference engine hooks engine.py:340-344 +
        # data_routing scheduler; here: per-layer token-subset indices as a
        # shape-carrying model kwarg — a kept-count change retraces exactly
        # like the curriculum seqlen schedule) ----------------------------
        self.random_ltd_scheduler = None
        self._ltd_layer_num = 0
        de_cfg = self._config.data_efficiency_config or {}
        routing = de_cfg.get("data_routing", {})
        ltd_cfg = routing.get("random_ltd", {})
        if de_cfg.get("enabled") and routing.get("enabled") and ltd_cfg.get("enabled"):
            from deepspeed_tpu.runtime.data_pipeline.data_routing import RandomLTDScheduler

            sched = ltd_cfg.get("random_ltd_schedule", {})
            if "min_value" not in sched or "max_value" not in sched:
                raise ValueError(
                    "random_ltd.random_ltd_schedule needs min_value and "
                    "max_value (kept-token counts)"
                )
            scfg = sched.get("schedule_config", {})
            self.random_ltd_scheduler = RandomLTDScheduler(
                start_token_num=int(sched["min_value"]),
                max_token_num=int(sched["max_value"]),
                total_steps=int(scfg.get("require_steps", 1000)),
                step_size=int(scfg.get("seq_per_step", 16)),
            )
            self._ltd_layer_num = int(ltd_cfg.get("random_ltd_layer_num", 1))
            if self._ltd_layer_num < 1:
                raise ValueError(
                    f"random_ltd_layer_num={self._ltd_layer_num} must be >= 1 "
                    "(0 would silently disable the feature)"
                )
            if self._config.pld_config.enabled:
                raise ValueError(
                    "progressive_layer_drop and random_ltd cannot be combined"
                )

        # MoQ: in-step progressive weight quantization (reference
        # _configure_quantization engine.py:1330 + runtime/quantize.py;
        # distinct from compression/'s in-forward QAT) --------------------
        from deepspeed_tpu.runtime.quantize import moq_from_compression_config

        self.quantizer = moq_from_compression_config(self._config.compression_config)
        if self.quantizer is not None:
            if not (self._config.fp16_enabled or self._config.bfloat16_enabled):
                # reference: "MoQ ... is only supported for FP16" — the
                # compute store must be separate from the fp32 master it
                # anneals against
                raise ValueError(
                    "MoQ (quantize_weight_in_forward: false) requires fp16 "
                    "or bf16 mixed precision"
                )
            if self._offload_requested(self._config.zero_config.offload_param):
                raise NotImplementedError(
                    "MoQ is unsupported with ZeRO param offload (weights "
                    "live in the layer stream, not the HBM compute store)"
                )

        # flops profiler (reference engine.py:574-598 wiring) -------------
        self.flops_profiler = None
        self._last_profile_args = None
        if self._config.flops_profiler_config.enabled:
            from deepspeed_tpu.profiling.flops_profiler.profiler import FlopsProfiler

            self.flops_profiler = FlopsProfiler(ds_engine=self)

        # monitor --------------------------------------------------------
        self.monitor = None
        if self._config.monitor_config.active:
            from deepspeed_tpu.monitor.monitor import MonitorMaster

            self.monitor = MonitorMaster(self._config.monitor_config)

        # checkpoint engine ----------------------------------------------
        self.checkpoint_engine = OrbaxCheckpointEngine(self._config)
        # async atomic checkpointing (checkpoint.async_snapshot): created
        # lazily on the first async save; double-buffered background writer
        self._ckpt_writer: Optional[AsyncCheckpointWriter] = None
        self._ckpt_metrics = {
            "saves": 0,
            "async_saves": 0,
            "last_stall_ms": 0.0,  # device->host snapshot time (async path)
            "total_stall_ms": 0.0,
            "last_save_s": 0.0,  # full persist wall time (staging+commit)
            "last_restore_s": 0.0,
        }

        # state (lazily initialized on first batch or from model_parameters)
        self._initialized = False
        self._params = None  # compute-dtype tree
        self._master = None  # fp32 master tree (is _params when not mixed / stage0 fp32)
        self._opt_state = None
        self._grad_acc = None
        self._scale_state: Optional[LossScaleState] = None
        self._rng = jax.random.PRNGKey(self._config.seed if self._config.seed is not None else 42)
        self._last_loss = None
        self._last_grad_norm = None
        self._overflow = False
        self._pending_model_parameters = model_parameters

        self._host_offload = None
        self._streamed_offload = False  # ZeRO-Infinity streamed master/moments
        self._jit_offload_stats = None
        self._jit_offload_bucket = []  # one donated update program per bucket
        self._param_stream = None  # ZeRO-Infinity layer-streamed param offload
        self._stream_scale = 1.0
        self.partitioner: Optional[ZeroPartitioner] = None
        self._fused_step_enabled = False
        self._fused_accum_enabled = False
        self._pending_commit = None
        self._jit_fused_step = None
        self._jit_fused_accum_step = None
        self._profile_fn = None
        self._last_batch = None
        self._last_fwd_rng = None
        self._last_model_kwargs = None
        self._last_fwd_scale = None
        self._overlap_plan = None
        self._jit_debug_grad = None
        self._jit_fwd_bwd = None
        self._jit_eval = None
        self._jit_step = None
        self._batch_spec_fn = None

        # multi-step training windows (compile.multi_step; ISSUE 14):
        # N full optimizer steps per dispatch, with the per-step results
        # stashed device-side and committed one train_batch call at a time
        self._jit_fused_window_step = None
        self._window_armed = False
        self._window_horizon = 0
        self._window_stash: deque = deque()  # computed-but-uncommitted steps
        self._pending_drains: deque = deque()  # deferred per-window loss drains
        self._drained_log: deque = deque(maxlen=4096)
        self._drained_dropped = 0  # entries the bounded log evicted unread
        self._window_metrics = {
            "window_steps": 0,
            "windowed_opt_steps": 0,
            "window_break_reasons": {
                "checkpoint": 0,  # a checkpoint-interval boundary inside the horizon
                "monitor": 0,  # a monitor flush inside the horizon
                "data": 0,  # dataloader exhausted before a full window
                "profiler": 0,  # the flops-profiler step wants the unfused path
            },
        }
        self._active_prefetcher = None  # PrefetchingLoader for the live data_iter
        self._prefetch_key = None

        # compile telemetry: every jitted program is instrumented so
        # trace/compile/dispatch counts (and retrace regressions) are
        # observable via compile_stats(); opt-in persistent compilation
        # cache so repeated runs skip cold compiles
        self._telemetry = CompileTelemetry()
        ccfg = self._config.compile_config
        if ccfg.cache_dir:
            configure_persistent_cache(ccfg.cache_dir, ccfg.cache_min_compile_secs)
        # analysis.verify: run the static program passes against each
        # program right after its first compile (warn or raise) — the
        # donation/dtype/host-transfer/comms guarantees are checked where
        # they are created, not rediscovered in a bench regression
        acfg = self._config.analysis_config
        if acfg.verify != "off":
            self._telemetry.on_compile = self._verify_program_static

        # multi-step window validation + observability: structural conflicts
        # fail at construction (an armed knob that silently never windows is
        # worse than an error), and window_stats rides the merged report
        self._validate_multi_step()
        self._obs_hub.add_source("train_window", self.window_stats)
        if self._config.compile_config.multi_step.enable and self._obs_hub.flight_recorder is not None:
            # postmortems must name the window config (a crash dump showing a
            # train.window span is only readable next to the armed horizon)
            self._obs_hub.flight_recorder.context["train.multi_step"] = {
                "enable": True,
                "horizon": int(self._config.compile_config.multi_step.horizon),
            }

        self.training_dataloader = self.deepspeed_io(training_data) if training_data is not None else None

        log_dist(
            f"DeepSpeedEngine configured: zero_stage={self.zero_optimization_stage()} "
            f"dtype={self.compute_dtype.__name__ if hasattr(self.compute_dtype, '__name__') else self.compute_dtype} "
            f"mesh={dict(zip(self.mesh.axis_names, self.mesh.devices.shape))} "
            f"batch triad=({self.train_batch_size()},{self.train_micro_batch_size_per_gpu()},{self.gradient_accumulation_steps()})",
            ranks=[0],
        )

    # ------------------------------------------------------------------
    # configuration accessors (reference API parity)
    # ------------------------------------------------------------------
    def train_batch_size(self) -> int:
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self._config.gradient_accumulation_steps

    def set_train_batch_size(self, train_batch_size: int) -> None:
        """Resize the global batch by changing the number of micro-batches
        (gradient-accumulation steps); the micro-batch size is unchanged
        (reference engine.py:403 — the elasticity resize hook).

        Structural here: gas=1 fuses the optimizer into the forward program
        and gas>1 accumulates into a buffer, so crossing that boundary
        rebuilds the jitted programs and (de)allocates the accumulator."""
        micro = self.train_micro_batch_size_per_gpu()
        dp = max(1, self.data_parallel_world_size())
        if train_batch_size % (micro * dp) != 0:
            raise ValueError(
                "Train batch size must be divisible by micro-batch * data "
                f"parallelism ({micro} * {dp})"
            )
        new_gas = train_batch_size // (micro * dp)
        if new_gas < 1:
            raise ValueError(
                f"train_batch_size={train_batch_size} is below one micro-batch "
                f"per data shard ({micro} * {dp})"
            )
        if new_gas == self.gradient_accumulation_steps():
            self._config.train_batch_size = train_batch_size
            self.tput_timer.batch_size = train_batch_size
            return
        self._check_resize_allowed()
        if (
            self._config.compile_config.multi_step.enable
            and new_gas > 1
            and not self._config.compile_config.fuse_grad_accum
        ):
            # same contract _validate_multi_step enforces at construction:
            # a resize must not silently disarm the windows (the rebuild
            # would set _window_armed False and never count a break)
            raise ValueError(
                f"set_train_batch_size: gradient_accumulation_steps={new_gas} "
                "with compile.multi_step enabled requires "
                "compile.fuse_grad_accum (the window scans the fused "
                "grad-accum body)"
            )
        if self._is_pipe_engine:
            # the pipeline folds all microbatches into one compiled schedule
            # sized at construction — a live resize cannot reshape it
            raise NotImplementedError(
                "set_train_batch_size is unsupported on the pipeline engine"
            )
        self._config.train_batch_size = train_batch_size
        self._config.gradient_accumulation_steps = new_gas
        self._gas_divisor = new_gas
        # re-base the window counter: boundary math is micro_steps % gas,
        # and an old count that is not a multiple of the NEW gas would make
        # the first window short with a wrong 1/gas divisor
        self.micro_steps = 0
        self.tput_timer.batch_size = train_batch_size
        if self._initialized:
            self.invalidate_compiled_step()
            if self._fused_step_enabled or self._fused_accum_enabled:
                self._grad_acc = None
            elif self._grad_acc is None:
                self._grad_acc = self._alloc_grad_acc()
        log_dist(
            f"set_train_batch_size: train_batch={train_batch_size} gas={new_gas}",
            ranks=[0],
        )

    def _check_resize_allowed(self) -> None:
        if self._in_forward or self._pending_commit is not None:
            raise RuntimeError("cannot resize the batch mid-step: finish backward()+step() first")
        if self._window_stash:
            raise RuntimeError(
                "cannot resize the batch mid-window: the multi-step window's "
                "remaining train_batch calls must commit first"
            )
        if self.micro_steps % self.gradient_accumulation_steps() != 0:
            raise RuntimeError(
                "cannot resize the batch inside an accumulation window: "
                "step() must complete the current window first"
            )
        if self._param_stream is not None or self._host_offload is not None:
            raise NotImplementedError(
                "batch resizing is unsupported on the offload paths"
            )

    def _alloc_grad_acc(self):
        """Zeroed gradient-accumulation buffer in the configured dtype with
        the grad shardings (used at init and after a gas resize)."""
        acc_dtype = self._grad_accum_dtype()
        zeros_acc = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda x: jnp.zeros(x.shape, acc_dtype), t),
            out_shardings=self._grad_shardings,
        )
        return zeros_acc(self._params)

    def set_train_micro_batch_size(self, micro_batch_size: int) -> None:
        """Change the micro-batch size, keeping gas fixed (reference
        engine.py:421). Shapes change, so the jitted programs retrace on the
        next forward automatically; only the config bookkeeping lives here."""
        if micro_batch_size < 1:
            raise ValueError(f"micro_batch_size={micro_batch_size} must be >= 1")
        if self._is_pipe_engine:
            # the pipeline schedule (tick count, stage buffers) is sized at
            # construction — mirroring set_train_batch_size's guard
            raise NotImplementedError(
                "set_train_micro_batch_size is unsupported on the pipeline engine"
            )
        self._check_resize_allowed()
        gas = self.gradient_accumulation_steps()
        dp = max(1, self.data_parallel_world_size())
        self._config.train_batch_size = micro_batch_size * gas * dp
        self._config.train_micro_batch_size_per_gpu = micro_batch_size
        self.tput_timer.batch_size = self._config.train_batch_size

    def zero_optimization_stage(self) -> int:
        return self._config.zero_optimization_stage

    def zero_optimization(self) -> bool:
        return self._config.zero_enabled

    def fp16_enabled(self) -> bool:
        return self._config.fp16_enabled

    def bfloat16_enabled(self) -> bool:
        return self._config.bfloat16_enabled

    def gradient_clipping(self) -> float:
        return self._config.gradient_clipping

    def data_parallel_world_size(self) -> int:
        return self.topology.get_data_parallel_world_size()

    @property
    def loss_scale(self) -> float:
        if self._scale_state is None:
            return self.loss_scaler.init_scale
        return float(jax.device_get(self._scale_state.scale))

    def get_lr(self):
        return self.optimizer.get_lr()

    def set_data_post_process_func(self, post_process_func) -> None:
        """Install a per-batch transform on the engine dataloader
        (reference engine.py:433 — the data-efficiency post-process hook)."""
        if self.training_dataloader is None:
            raise ValueError(
                "set_data_post_process_func needs an engine-owned dataloader: "
                "pass training_data to initialize() (a silently dropped hook "
                "would train on unprocessed batches)"
            )
        self.training_dataloader.post_process_func = post_process_func

    def set_custom_curriculum_learning_schedule(self, schedule_func_dict) -> None:
        """Install custom curriculum schedule functions (reference
        engine.py:437): a bare callable drives the engine's (seqlen)
        scheduler; the reference's {metric_name: fn} dict routes per metric —
        'seqlen' to the engine scheduler, any other single metric to the
        curriculum data sampler's scheduler."""
        if callable(schedule_func_dict):
            if self.curriculum_scheduler is None:
                raise ValueError("curriculum learning is not enabled")
            self.curriculum_scheduler.set_custom_get_difficulty(schedule_func_dict)
            return
        if not isinstance(schedule_func_dict, dict):
            raise TypeError(
                "expected a callable or a {metric_name: schedule_fn} dict, "
                f"got {type(schedule_func_dict).__name__}"
            )
        sampler = getattr(self.training_dataloader, "data_sampler", None)
        sampler_sched = getattr(sampler, "scheduler", None)
        for metric, fn in schedule_func_dict.items():
            if not callable(fn):
                raise TypeError(f"schedule for metric {metric!r} is not callable")
            if metric in ("seqlen", "default") and self.curriculum_scheduler is not None:
                self.curriculum_scheduler.set_custom_get_difficulty(fn)
            elif sampler_sched is not None:
                sampler_sched.set_custom_get_difficulty(fn)
            elif self.curriculum_scheduler is not None:
                self.curriculum_scheduler.set_custom_get_difficulty(fn)
            else:
                raise ValueError(
                    f"no curriculum scheduler to receive metric {metric!r} "
                    "(enable curriculum_learning or use a curriculum sampler)"
                )

    def get_global_grad_norm(self) -> Optional[float]:
        if self._last_grad_norm is None:
            return None
        return float(jax.device_get(self._last_grad_norm))

    def is_gradient_accumulation_boundary(self) -> bool:
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def train(self, mode: bool = True):
        if not mode and self._pending_commit is not None:
            raise RuntimeError(
                "eval() called with a pending fused step: with "
                "gradient_accumulation_steps=1 forward() already applied the "
                "optimizer update; call step() before switching to eval"
            )
        if not mode and self._window_stash:
            raise RuntimeError(
                "eval() called with a multi-step window mid-flight: the "
                "fused window already advanced the model state but "
                f"{len(self._window_stash)} step(s) are uncommitted; finish "
                "the window's train_batch calls before switching to eval"
            )
        if not mode and self._training_mode:
            # a half-open throughput window would count eval wall-clock
            self.tput_timer.abort_window()
        self._training_mode = mode
        return self

    def eval(self):
        return self.train(False)

    # ------------------------------------------------------------------
    # optimizer / scheduler wiring
    # ------------------------------------------------------------------
    def _configure_optimizer(self) -> DSOptimizer:
        if self.client_optimizer is not None:
            if not isinstance(self.client_optimizer, DSOptimizer):
                raise TypeError(
                    "client optimizer must be a deepspeed_tpu DSOptimizer (functional update rule)"
                )
            log_dist("Using client optimizer", ranks=[0])
            return self.client_optimizer
        opt_cfg = self._config.optimizer_config
        if opt_cfg is None or not opt_cfg.type:
            log_dist("No optimizer configured; defaulting to FusedAdam(lr=1e-3)", ranks=[0])
            return FusedAdam(lr=1e-3)
        name = opt_cfg.type.lower()
        cls = _OPTIMIZER_REGISTRY.get(name)
        if cls is None:
            raise ValueError(f"Unknown optimizer {opt_cfg.type!r}")
        params = dict(opt_cfg.params)
        params.pop("torch_adam", None)
        if "betas" in params:
            params["betas"] = tuple(params["betas"])
        return cls(**params)

    def _configure_lr_scheduler(self):
        if self.client_lr_scheduler is not None:
            if callable(self.client_lr_scheduler):
                return self.client_lr_scheduler(self.optimizer)
            return self.client_lr_scheduler
        sched_cfg = self._config.scheduler_config
        if sched_cfg is None or not sched_cfg.type:
            return None
        return get_lr_scheduler(sched_cfg.type, self.optimizer, **sched_cfg.params)

    # ------------------------------------------------------------------
    # dataloader
    # ------------------------------------------------------------------
    def deepspeed_io(self, dataset, batch_size=None, route=None, pin_memory=True, data_sampler=None, collate_fn=None, num_local_io_workers=None):  # noqa: ARG002
        from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size or self.train_micro_batch_size_per_gpu() * self.data_parallel_world_size(),
            collate_fn=collate_fn or self.collate_fn,
        )

    # ------------------------------------------------------------------
    # state initialization
    # ------------------------------------------------------------------
    def init_params(self, batch: Any, rng: Optional[jax.Array] = None) -> None:
        """Materialize sharded params/master/opt-state from a sample batch."""
        if self._initialized:
            return
        if rng is not None:
            self._rng = rng
        if self._param_offload_enabled():
            self._init_param_stream(batch)
            return
        placed = self._place_batch(batch)
        param_shapes = jax.eval_shape(lambda r, b: self.module.init(r, b), self._rng, placed)
        tp_rules = self.module.tp_partition_rules(param_shapes)
        self.partitioner = ZeroPartitioner(self._config.zero_config, self.topology, tp_rules)

        self._param_specs = self.partitioner.param_specs(param_shapes)
        self._master_specs = self.partitioner.master_specs(param_shapes)
        self._grad_specs = self.partitioner.grad_accum_specs(param_shapes)
        # donation-safe: the step programs donate the full state tuple, so
        # their out_shardings must repeat these input shardings exactly or
        # the in-place update degrades to a double-buffering copy
        param_shardings, master_shardings, grad_shardings = (
            self.partitioner.donation_out_shardings(
                self._param_specs, self._master_specs, self._grad_specs
            )
        )
        self._param_shardings = param_shardings
        self._master_shardings = master_shardings
        self._grad_shardings = grad_shardings

        if self._pending_model_parameters is not None:
            src = self._pending_model_parameters
            master = jax.tree_util.tree_map(lambda p: jnp.asarray(p, dtype=jnp.float32), src)
            master = jax.jit(lambda t: t, out_shardings=master_shardings)(master)
        else:
            def _sharded_init(r, b):
                p = self.module.init(r, b)
                return jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), p)

            master = jax.jit(_sharded_init, out_shardings=master_shardings)(self._rng, placed)

        if self.mixed_precision:
            keep32 = self.module.keep_fp32_params(param_shapes) if hasattr(self.module, "keep_fp32_params") else None
            self._keep_fp32 = keep32
            if keep32 is None:
                cast_tree = lambda t: jax.tree_util.tree_map(lambda x: x.astype(self.compute_dtype), t)
            else:
                cast_tree = lambda t: jax.tree_util.tree_map(
                    lambda x, keep: x if keep else x.astype(self.compute_dtype), t, keep32
                )
            self._params = jax.jit(cast_tree, out_shardings=param_shardings)(master)
            self._master = master
        else:
            # fp32 training: one copy, stored with the ZeRO MASTER sharding
            # from step 0 — the step programs donate it with master
            # out-shardings, so any other initial placement makes the first
            # step's donation unaliasable (double-buffer copy + "donated
            # buffers were not usable" warning) and retraces the second step
            # when the output sharding differs from the input's.
            self._params = jax.jit(lambda t: t, out_shardings=master_shardings)(master)
            self._master = self._params

        if self._offload_enabled():
            offcfg = self._config.zero_config.offload_optimizer
            self._validate_host_adam("offload_optimizer")
            if offcfg.pipeline and str(offcfg.device.value) == "cpu":
                # ZeRO-Infinity STREAMED path (runtime/zero/host_offload.py):
                # fp32 master + moments live in host buffers and stream
                # device-ward per bucket through the depth-2 pipeline; the
                # per-bucket donated device program applies the exact fused
                # update math, so the device Adam — not a host reimplementation
                # — remains the single source of step arithmetic.
                from deepspeed_tpu.runtime.zero.host_offload import HostOffloadStreamer

                self._host_offload = HostOffloadStreamer(
                    master,
                    offcfg,
                    mixed_precision=self.mixed_precision,
                    clock=self.tracer.clock,
                )
                self._streamed_offload = True
                # the window program (compile.multi_step) still needs the
                # device-side opt shardings to rebuild/donate gathered state
                opt_specs = self.optimizer.state_specs(self._master_specs)
                self._opt_shardings = jax.tree_util.tree_map(
                    lambda s: NamedSharding(self.mesh, s),
                    opt_specs,
                    is_leaf=lambda x: isinstance(x, PartitionSpec),
                )
                # free the device-side master: the host copy is authoritative
                self._master = None
                self._opt_state = None
            else:
                # legacy ZeRO-Offload: fp32 master + moments leave the chip —
                # host DRAM (device=cpu) or local SSD (device=nvme) via the
                # native AVX Adam + aio swapper (runtime/zero/offload_states.py)
                from deepspeed_tpu.runtime.zero.offload_states import HostOffloadAdam

                opt_cfg = self._config.optimizer_config
                params_cfg = dict(opt_cfg.params) if opt_cfg is not None else {}
                self._host_offload = HostOffloadAdam(
                    master,
                    self.compute_dtype,
                    offcfg,
                    aio_param_dict=self._config._param_dict,
                    betas=tuple(params_cfg.get("betas", (0.9, 0.999))),
                    eps=params_cfg.get("eps", 1e-8),
                    weight_decay=params_cfg.get("weight_decay", 0.0),
                    adamw_mode=params_cfg.get("adam_w_mode", True),
                )
                self._host_offload.set_param_dtypes(
                    [l.dtype for l in jax.tree_util.tree_leaves(self._params)]
                )
                # free the device-side master: the host copy is authoritative now
                self._master = None
                self._opt_state = None
                self._opt_shardings = None
        else:
            self._host_offload = None
            opt_specs = self.optimizer.state_specs(self._master_specs)
            opt_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                opt_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec),
            )
            self._opt_state = jax.jit(self.optimizer.init_state, out_shardings=opt_shardings)(self._master)
            self._opt_shardings = opt_shardings

        self._scale_state = jax.device_put(self.loss_scaler.init_state())
        self._build_jitted_fns()
        if not self._fused_step_enabled and not self._fused_accum_enabled:
            # accumulation buffer only exists when micro-steps accumulate
            # across calls; the fused paths (gas=1 fused step, or the
            # fuse_grad_accum scan) keep grads inside one program.
            # dtype follows data_types.grad_accum_dtype (reference
            # engine.py get_data_types; fp32 default — bf16 halves the
            # buffer for gas>1 at reduced accumulation precision)
            self._grad_acc = self._alloc_grad_acc()
        self._initialized = True
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(self._params))
        log_dist(f"Initialized model state: {n_params:,} parameters", ranks=[0])

    def _batch_pspec(self, batch) -> Any:
        """Batch sharding: leading dim over the dense-DP axes, dim 1 (sequence)
        over the sequence axis when SP is on."""
        dp_axes = self.topology.dense_batch_axes()
        seq = self.topology.config.sequence > 1

        def leaf_spec(x):
            nd = np.ndim(x)
            if nd == 0:
                return PartitionSpec()
            entries = [dp_axes]
            if nd >= 2 and seq:
                entries.append("sequence")
            entries += [None] * (nd - len(entries))
            return PartitionSpec(*entries)

        return jax.tree_util.tree_map(leaf_spec, batch)

    def _stacked_batch_pspec(self, stacked) -> Any:
        """Batch pspec with a leading UNSHARDED gas dim (the fused program's
        scan axis); each microbatch slice shards dim 1 over the dense-DP
        axes and (under SP) dim 2 over the sequence axis."""
        dp_axes = self.topology.dense_batch_axes()
        seq = self.topology.config.sequence > 1

        def leaf_spec(x):
            nd = np.ndim(x)
            if nd <= 1:
                return PartitionSpec()
            entries = [None, dp_axes]
            if nd >= 3 and seq:
                entries.append("sequence")
            entries += [None] * (nd - len(entries))
            return PartitionSpec(*entries)

        return jax.tree_util.tree_map(leaf_spec, stacked)

    def _place_stacked_batch(self, micro):
        """Stack gas microbatches along a new leading scan dim and place the
        result as one global array. Host batches stack on the host; already-
        placed single-process jax arrays stack on device and are re-put so
        the fused program always sees the SAME input sharding (a drifting
        input sharding would retrace it)."""
        leaves = jax.tree_util.tree_leaves(micro[0])
        if leaves and all(isinstance(x, jax.Array) for x in leaves) and jax.process_count() == 1:
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *micro)
        else:
            if jax.process_count() > 1 and any(
                isinstance(x, jax.Array) and not x.is_fully_addressable
                for b in micro
                for x in jax.tree_util.tree_leaves(b)
            ):
                # host stacking would np.asarray a non-addressable global
                # array; fail with the actual contract instead
                raise NotImplementedError(
                    "fuse_grad_accum on multi-process runs requires host "
                    "(numpy) microbatches; pre-placed global jax.Array "
                    "batches cannot be re-stacked across hosts — feed host "
                    "batches or disable compile.fuse_grad_accum"
                )
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *micro
            )
        return self._place_batch(stacked, specs=self._stacked_batch_pspec(stacked))

    def _place_batch(self, batch, specs=None):
        """Device-put a host batch as a global sharded array. An explicit
        ``specs`` tree forces (re)placement even of already-placed arrays."""
        if specs is None:
            if all(isinstance(x, jax.Array) for x in jax.tree_util.tree_leaves(batch)):
                return batch
            specs = self._batch_pspec(batch)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
        )
        if jax.process_count() == 1:
            return jax.device_put(batch, shardings)

        # Multi-host: every host holds the same GLOBAL batch (the dataloader
        # is deterministic across hosts); each device picks its slice, so no
        # sample is duplicated and the global shape equals the batch shape.
        def place(leaf, sharding):
            arr = np.asarray(leaf)
            return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])

        return jax.tree_util.tree_map(
            place, batch, shardings, is_leaf=lambda x: isinstance(x, np.ndarray)
        )

    def _grad_accum_dtype(self):
        """Accumulation dtype from data_types.grad_accum_dtype (reference
        config: None→fp32 default)."""
        name = self._config.data_types_config.grad_accum_dtype
        if name is None:
            return jnp.float32
        table = {"fp32": jnp.float32, "float32": jnp.float32,
                 "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                 "fp16": jnp.float16, "float16": jnp.float16}
        if str(name) not in table:
            raise ValueError(
                f"data_types.grad_accum_dtype={name!r} is not one of "
                "fp32/bf16/fp16"
            )
        dtype = table[str(name)]
        if dtype == jnp.float16 and not self._config.fp16_enabled:
            # overflow detection is gated on the fp16 flag: an fp16 buffer
            # without it would feed silent infs into the optimizer
            raise ValueError(
                "grad_accum_dtype=fp16 requires fp16.enabled (overflow "
                "detection covers fp16 accumulation only on the fp16 path)"
            )
        zcfg = self._config.zero_config
        if dtype != jnp.float32 and (
            zcfg.zero_quantized_gradients
            or self._offload_requested(zcfg.offload_optimizer)
            or self._offload_requested(zcfg.offload_param)
        ):
            raise NotImplementedError(
                "non-fp32 grad_accum_dtype is unsupported with quantized "
                "gradients (qgZ) or offloaded optimizer/param state (those "
                "paths assume fp32 accumulation buffers)"
            )
        return dtype

    def _model_kwargs(self, placed=None):
        """Per-step traced model kwargs (reference engine.py:1772-1785 kwarg
        injection). PLD theta is a scalar whose VALUE changes (no retrace);
        random-LTD indices are arrays whose SHAPE changes with the schedule
        (retrace per kept-count bucket, like the curriculum seqlen)."""
        kwargs = {}
        if self.progressive_layer_drop is not None:
            kwargs["pld_theta"] = jnp.float32(self.progressive_layer_drop.get_theta())
        if self.random_ltd_scheduler is not None and placed is not None:
            from deepspeed_tpu.runtime.data_pipeline.data_routing import (
                sample_layer_token_indices,
            )

            tokens = jax.tree_util.tree_leaves(placed)[0]
            B, T = int(tokens.shape[0]), int(tokens.shape[1])
            kept = min(self.random_ltd_scheduler.current, T)
            if kept < T:
                self._rng, sub = jax.random.split(self._rng)
                kwargs["ltd_idx"] = sample_layer_token_indices(
                    sub, self._ltd_layer_num, B, T, kept
                )
        return kwargs

    # ------------------------------------------------------------------
    # jitted programs
    # ------------------------------------------------------------------
    _JIT_ATTRS = (
        "_jit_fwd_bwd",
        "_jit_eval",
        "_jit_step",
        "_jit_fused_step",
        "_jit_fused_accum_step",
        "_jit_fused_window_step",
        "_jit_debug_grad",
        "_jit_grad_stats",
        "_jit_zero_grads",
        "_jit_reshard_params",
    )

    def invalidate_compiled_step(self) -> None:
        """Re-trace the step programs on the next call AND release the stale
        executables. For wrappers whose apply() reads Python-level state at
        TRACE time (compression staging: ``CompressedModule.active_rows``
        flips at ``schedule_offset``) — the cached executables would
        otherwise keep the old forward forever. The elastic-resize path uses
        the same rebuild.

        Rebinding the attributes alone is NOT enough: jit keeps the old
        executable alive in its cache, and accumulated stale executables
        have wedged whole sessions (PERF.md round 5 — a micro-batch resize
        loop reproduces it). Each old callable's cache is cleared explicitly
        before the rebuild."""
        for name in self._JIT_ATTRS:
            fn = getattr(self, name, None)
            clear = getattr(fn, "clear_cache", None)
            if callable(clear):
                try:
                    clear()
                except Exception:
                    pass  # release is best-effort; the rebuild still detaches
            setattr(self, name, None)
        if self._initialized and self._param_stream is None:
            self._build_jitted_fns()

    def _build_jitted_fns(self) -> None:
        module = self.module
        grad_specs = self._grad_specs
        mesh = self.mesh
        gas = self._gas_divisor
        clip = self._config.gradient_clipping
        fp16 = self._config.fp16_enabled
        scaler = self.loss_scaler
        optimizer = self.optimizer
        compute_dtype = self.compute_dtype
        mixed = self.mixed_precision

        def base_loss_of(params, batch, rng, model_kwargs=None):
            # model_kwargs carries per-step traced scalars (pld_theta) without
            # retracing: the dict structure is static, the values are arrays
            out = module.apply(
                params, batch, rngs={"dropout": rng}, train=True, **(model_kwargs or {})
            )
            if isinstance(out, tuple):
                return out[0]
            return out

        # ZeRO++ (reference zero/config.py:260-272; validated in __init__)
        zcfg = self._config.zero_config
        qwz = bool(zcfg.zero_quantized_weights)
        qgz = bool(zcfg.zero_quantized_gradients)
        if qwz:
            from deepspeed_tpu.runtime.zero.zeropp import qwz_gather_tree

            param_specs = self._param_specs
            topo = self.topology

            def loss_of(params, batch, rng, model_kwargs=None):
                # qwZ: the stage-3 param gathers carry int8 (GSPMD boundary)
                return base_loss_of(
                    qwz_gather_tree(params, param_specs, topo), batch, rng, model_kwargs
                )
        else:
            loss_of = base_loss_of

        # fp32 training stores the ZeRO-sharded fp32 master AS the compute
        # params (one tree, threshold-0 master layout) — which silently
        # defeats stage3_param_persistence_threshold: leaves the partitioner
        # keeps replicated under mixed precision arrive sharded, and their
        # use-point gathers land INSIDE the remat'd backward scan, where the
        # first-op norm scales have no independent compute to hide behind
        # (the overlap pass flags them as exposed loop collectives). Re-pin
        # the training/eval view of the tree to the persistence-honoring
        # param specs before the forward: persistent leaves materialize
        # replicated ONCE per step outside the scan, non-persistent leaves
        # keep the master layout (their param spec is the same sharded one).
        # Value-preserving; a no-op under mixed precision (params already
        # carry param_specs) and when the threshold is 0.
        if mixed:
            pin_persistent = lambda p: p  # noqa: E731
        else:
            _pspecs = self._param_specs

            def pin_persistent(params):
                return jax.tree_util.tree_map(
                    lambda t, s: jax.lax.with_sharding_constraint(t, NamedSharding(mesh, s)),
                    params,
                    _pspecs,
                    is_leaf=lambda x: isinstance(x, PartitionSpec),
                )

        # comm-overlap plan (runtime/zero/overlap.py): activated trace-time
        # around every training loss, so the scanned layer stack pipelines
        # its stage-3 param gathers (layer i+1's all-gather issued during
        # layer i's compute) and reduce-scatters each layer's grads in
        # reduce_bucket_size buckets inside the backward scan instead of one
        # tail barrier. Value-preserving by construction — the parity suite
        # holds it bit-identical. qwZ/qgZ own their gather/reduce wire
        # formats and stay unpipelined.
        self._overlap_plan = self._build_overlap_plan(qwz=qwz, qgz=qgz)
        if self._overlap_plan is not None:
            from deepspeed_tpu.runtime.zero.overlap import overlap_scope

            inner_loss_of = loss_of

            def loss_of(params, batch, rng, model_kwargs=None):
                with overlap_scope(self._overlap_plan):
                    return inner_loss_of(params, batch, rng, model_kwargs)

        # XLA latency-hiding scheduler for the step-flavor programs: the
        # compiler half of the overlap story (the pipeline creates the
        # independent work; the scheduler interleaves it with the DMAs).
        # TPU-only and version-gated — the telemetry wrapper drops
        # compiler_options where this jax's jit cannot take them.
        step_opts = self._overlap_compiler_options()
        step_jit_extra = {"compiler_options": step_opts} if step_opts else {}

        # the debug-grad surface (get_last_grads) must differentiate the SAME
        # loss contract the step uses
        self._loss_of = loss_of

        def fwd_bwd(params, grad_acc, scale, rng, batch, model_kwargs):
            params = pin_persistent(params)

            def scaled_loss(p):
                return loss_of(p, batch, rng, model_kwargs) * scale.astype(jnp.float32)

            loss_scaled, grads = jax.value_and_grad(scaled_loss)(params)
            # accumulate in the buffer's dtype (grad_accum_dtype; fp32 default)
            new_acc = jax.tree_util.tree_map(
                lambda a, g, s: jax.lax.with_sharding_constraint(a + g.astype(a.dtype), NamedSharding(mesh, s)),
                grad_acc,
                grads,
                grad_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec),
            )
            return loss_scaled / scale.astype(jnp.float32), new_acc

        if qgz:
            # qgZ: explicit shard_map grad path — both reduction hops int8
            from deepspeed_tpu.runtime.zero.zeropp import (
                build_qgz_fwd_bwd,
                validate_qgz_mesh,
            )

            validate_qgz_mesh(self.topology)
            qgz_fwd_bwd = build_qgz_fwd_bwd(
                base_loss_of,
                self.topology,
                self._param_specs,
                self._grad_specs,
                self._batch_pspec,
                qwz=qwz,
            )

            def fwd_bwd(params, grad_acc, scale, rng, batch, model_kwargs):
                if model_kwargs:  # static structure check at trace time
                    raise NotImplementedError(
                        "per-step model kwargs (progressive_layer_drop) are "
                        "unsupported with zero_quantized_gradients"
                    )
                return qgz_fwd_bwd(params, grad_acc, scale, rng, batch)

        # donation on fwd_bwd covers its only DYING input, the accumulator;
        # params and the loss scale stay live across the whole accumulation
        # window (every microbatch re-reads them), so they cannot be donated
        # here — full-state donation happens where the state actually turns
        # over: _jit_step and the fused programs below.
        self._jit_fwd_bwd = self._telemetry.instrument(
            "fwd_bwd", fwd_bwd, donate_argnums=(1,), **step_jit_extra
        )

        def eval_fwd(params, rng, batch):
            if qwz:
                from deepspeed_tpu.runtime.zero.zeropp import qwz_gather_tree

                params = qwz_gather_tree(params, self._param_specs, self.topology)
            out = module.apply(pin_persistent(params), batch, rngs={"dropout": rng}, train=False)
            return out

        self._jit_eval = self._telemetry.instrument("eval_fwd", eval_fwd)

        def update_from_grads(grads32, params, master, opt_state, scale_state, lr):
            """Shared optimizer-update body: unscaled fp32 grads → new state.

            Overflow check, global-norm clip, optimizer apply, overflow-revert
            (a ``where``, not a host sync), compute-dtype re-cast, loss-scale
            update. Used by both the standalone step and the fused micro-step
            so the update math lives in exactly one place."""
            overflow = has_inf_or_nan(grads32) if fp16 else jnp.zeros((), jnp.bool_)
            # global grad norm: full reductions over sharded leaves are global
            sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree_util.tree_leaves(grads32))
            grad_norm = jnp.sqrt(sq)
            if clip > 0:
                coef = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads32 = jax.tree_util.tree_map(lambda g: g * coef, grads32)
            new_master, new_opt = optimizer.apply(grads32, opt_state, master, jnp.float32(lr))
            new_master = jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new_master, master
            )
            new_opt = jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new_opt, opt_state
            )
            if mixed:
                # re-cast to each param's stored dtype (keep_fp32_params leaves
                # stay fp32; everything else is the compute dtype)
                new_params = jax.tree_util.tree_map(
                    lambda m, p: jnp.where(overflow, p, m.astype(p.dtype)), new_master, params
                )
            else:
                new_params = new_master
            new_scale_state = scaler.update(scale_state, overflow)
            return new_params, new_master, new_opt, new_scale_state, grad_norm, overflow

        def step_fn(params_or_none, master, opt_state, grad_acc, scale_state, lr):
            params = master if params_or_none is None else params_or_none
            inv = 1.0 / (scale_state.scale * gas)
            # the update math runs fp32 whatever the accumulation dtype was
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * inv, grad_acc
            )
            new_params, new_master, new_opt, new_scale_state, grad_norm, overflow = (
                update_from_grads(grads, params, master, opt_state, scale_state, lr)
            )
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, grad_acc)
            return new_params, new_master, new_opt, zeroed, new_scale_state, grad_norm, overflow

        # fully-fused micro-step: when every training forward IS a full step
        # (_gas_divisor == 1: dense gas=1, or the SPMD pipeline which folds
        # all microbatches into one fwd_bwd), run forward+backward+optimizer
        # as ONE jitted program. Grads never round-trip through the fp32
        # accumulation buffer, XLA overlaps the optimizer update with the
        # tail of the backward, and the host dispatches once per step —
        # this is the single biggest single-chip throughput lever on the
        # tunneled TPU backend (dispatch RTT is paid per program).
        self._fused_step_enabled = (
            self._gas_divisor == 1 and self._host_offload is None and not qgz
        )
        fused_acc_dtype = self._grad_accum_dtype()

        def full_step_core(params, master, opt_state, scale_state, lr, rng, data, model_kwargs):
            """ONE complete optimizer step: fwd+bwd (a scan over gas
            microbatches when gas>1), unscale, update. Shared by
            ``fused_step`` (gas=1), ``fused_accum_step`` (gas>1) AND the
            multi-step window body — the window's bit-identity guarantee
            (a window == N sequential train_batch calls) rests on all
            three running EXACTLY this math with EXACTLY this rng split
            schedule, so it lives in one place. ``data`` is the single
            microbatch at gas=1, the stacked ``[gas, ...]`` microbatches
            otherwise. Returns the new state plus the step's loss, grad
            norm, overflow flag, and pre-update scale."""
            params = pin_persistent(params)
            scale = scale_state.scale
            rng, sub = jax.random.split(rng)
            if gas == 1:

                def scaled_loss(p):
                    return loss_of(p, data, sub, model_kwargs) * scale.astype(jnp.float32)

                loss_scaled, grads = jax.value_and_grad(scaled_loss)(params)
                loss = loss_scaled / scale.astype(jnp.float32)
                inv = 1.0 / scale
                grads32 = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32) * inv, grads
                )
            else:
                micro_rngs = jax.random.split(sub, gas)

                def micro(acc, xs):
                    mb, r = xs

                    def scaled_loss(p):
                        return loss_of(p, mb, r, model_kwargs) * scale.astype(jnp.float32)

                    loss_scaled, g = jax.value_and_grad(scaled_loss)(params)
                    acc = jax.tree_util.tree_map(
                        lambda a, gg, s: jax.lax.with_sharding_constraint(
                            a + gg.astype(a.dtype), NamedSharding(mesh, s)
                        ),
                        acc,
                        g,
                        grad_specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec),
                    )
                    return acc, loss_scaled / scale.astype(jnp.float32)

                zero_acc = jax.tree_util.tree_map(
                    lambda p, s: jax.lax.with_sharding_constraint(
                        jnp.zeros(p.shape, fused_acc_dtype), NamedSharding(mesh, s)
                    ),
                    params,
                    grad_specs,
                    is_leaf=lambda x: isinstance(x, PartitionSpec),
                )
                acc, losses = jax.lax.scan(micro, zero_acc, (data, micro_rngs))
                loss = jnp.mean(losses)
                inv = 1.0 / (scale * gas)
                grads32 = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32) * inv, acc
                )
            new_params, new_master, new_opt, new_scale_state, grad_norm, overflow = (
                update_from_grads(grads32, params, master, opt_state, scale_state, lr)
            )
            return (
                new_params, new_master, new_opt, new_scale_state, rng,
                loss, grad_norm, overflow, scale,
            )

        def fused_step(params_or_none, master, opt_state, scale_state, lr, rng, batch, model_kwargs):
            params = master if params_or_none is None else params_or_none
            (new_params, new_master, new_opt, new_scale_state, rng,
             loss, grad_norm, overflow, scale) = full_step_core(
                params, master, opt_state, scale_state, lr, rng, batch, model_kwargs
            )
            # pre-update scale returned as an OUTPUT: scale_state is donated,
            # so the host cannot stash the input array (the buffer dies with
            # the call), yet the debug-grad recompute needs the exact scale
            # the step consumed
            return loss, new_params, new_master, new_opt, new_scale_state, grad_norm, overflow, scale, rng

        if self._fused_step_enabled:
            if mixed:
                self._jit_fused_step = self._telemetry.instrument(
                    "fused_step",
                    fused_step,
                    donate_argnums=(0, 1, 2, 3),
                    out_shardings=(
                        None,
                        self._param_shardings,
                        self._master_shardings,
                        self._opt_shardings,
                        None,
                        None,
                        None,
                        None,
                        None,
                    ),
                    **step_jit_extra,
                )
            else:
                def fp32_fused_step(master, opt_state, scale_state, lr, rng, batch, model_kwargs):
                    out = fused_step(None, master, opt_state, scale_state, lr, rng, batch, model_kwargs)
                    return out[0], out[2], out[3], out[4], out[5], out[6], out[7], out[8]

                self._jit_fused_step = self._telemetry.instrument(
                    "fused_step",
                    fp32_fused_step,
                    donate_argnums=(0, 1, 2),
                    out_shardings=(
                        None,
                        self._master_shardings,
                        self._opt_shardings,
                        None,
                        None,
                        None,
                        None,
                        None,
                    ),
                    **step_jit_extra,
                )
        else:
            self._jit_fused_step = None

        # fuse_grad_accum: the gas>1 hot path as ONE jitted program per
        # optimizer step — a lax.scan over the stacked microbatches running
        # fwd+bwd+accumulate (the accumulator is a scan carry, never an HBM
        # buffer the host holds), then the SAME update_from_grads body the
        # unfused step uses. One host dispatch per optimizer step instead of
        # gas+1, and XLA overlaps the update with the last microbatch's
        # backward. Engaged through train_batch(); the per-microbatch
        # forward/backward/step protocol falls back to the unfused programs.
        # qgZ stays unfused (its shard_map grad path manages its own
        # reduction schedule); the offload paths and random-LTD (per-micro
        # host-sampled index shapes) are structurally incompatible.
        self._fused_accum_enabled = (
            bool(self._config.compile_config.fuse_grad_accum)
            and gas > 1
            and self._host_offload is None
            and not qgz
            and self.random_ltd_scheduler is None
        )
        if self._fused_accum_enabled:

            def fused_accum_step(params_or_none, master, opt_state, scale_state, lr, rng, stacked, model_kwargs):
                params = master if params_or_none is None else params_or_none
                (new_params, new_master, new_opt, new_scale_state, rng,
                 loss, grad_norm, overflow, scale) = full_step_core(
                    params, master, opt_state, scale_state, lr, rng, stacked, model_kwargs
                )
                return loss, new_params, new_master, new_opt, new_scale_state, grad_norm, overflow, scale, rng

            if mixed:
                self._jit_fused_accum_step = self._telemetry.instrument(
                    "fused_accum_step",
                    fused_accum_step,
                    donate_argnums=(0, 1, 2, 3),
                    out_shardings=(
                        None,
                        self._param_shardings,
                        self._master_shardings,
                        self._opt_shardings,
                        None,
                        None,
                        None,
                        None,
                        None,
                    ),
                    **step_jit_extra,
                )
            else:
                def fp32_fused_accum_step(master, opt_state, scale_state, lr, rng, stacked, model_kwargs):
                    out = fused_accum_step(None, master, opt_state, scale_state, lr, rng, stacked, model_kwargs)
                    return out[0], out[2], out[3], out[4], out[5], out[6], out[7], out[8]

                self._jit_fused_accum_step = self._telemetry.instrument(
                    "fused_accum_step",
                    fp32_fused_accum_step,
                    donate_argnums=(0, 1, 2),
                    out_shardings=(
                        None,
                        self._master_shardings,
                        self._opt_shardings,
                        None,
                        None,
                        None,
                        None,
                        None,
                    ),
                    **step_jit_extra,
                )
        else:
            self._jit_fused_accum_step = None

        # multi-step training windows (compile.multi_step; ISSUE 14): ONE
        # jitted program running `horizon` FULL optimizer steps as a
        # lax.scan whose carry IS the donated state tuple — params, master,
        # opt_state AND the fp16 loss-scale state all thread through the
        # carry, so overflow-skip/rescale stays in-program and the donation
        # pass verifies the aliasing end to end. Each scanned step
        # replicates the sequential fused program's math exactly, including
        # its rng split schedule (gas=1 mirrors fused_step, gas>1 mirrors
        # fused_accum_step), so a window is bit-identical to N sequential
        # train_batch calls. Per-step lr values ride in as an array indexed
        # by an in-carry scheduler cursor that advances only on
        # non-overflow steps — exactly when the host lr scheduler would
        # have stepped. Host-relevant per-step results (loss, grad norm,
        # overflow) return as N scalars each so the host can
        # copy_to_host_async them and drain one window deferred; slicing a
        # device array post-hoc would dispatch tiny gather programs the
        # compile-telemetry gates forbid.
        mscfg = self._config.compile_config.multi_step
        # streamed host offload composes with windows: the window program
        # runs the on-device fused step body over GATHERED master/moments
        # (see _try_train_window), so the offload-disabled fused flags don't
        # gate it — the same construction conditions do, minus offload.
        _streamed_window_ok = self._streamed_offload and not qgz and (
            gas == 1
            or (
                bool(self._config.compile_config.fuse_grad_accum)
                and self.random_ltd_scheduler is None
            )
        )
        self._window_armed = bool(
            mscfg.enable
            and (
                (self._fused_step_enabled if gas == 1 else self._fused_accum_enabled)
                or _streamed_window_ok
            )
        )
        self._window_horizon = int(mscfg.horizon) if self._window_armed else 0
        if self._window_armed:
            H = int(mscfg.horizon)

            def fused_window_step(params_or_none, master, opt_state, scale_state, lrs, rng, stacked):
                params = master if params_or_none is None else params_or_none
                if gas > 1:
                    # [H*gas, B, ...] -> [H, gas, B, ...]: both leading dims
                    # are unsharded (the batch dim carries the DP split), so
                    # the reshape is resharding-free
                    stacked = jax.tree_util.tree_map(
                        lambda x: x.reshape((H, gas) + x.shape[1:]), stacked
                    )

                def one_step(carry, mb):
                    params, master, opt, sstate, rng, sched = carry
                    lr = jnp.take(lrs, sched)
                    rng_in = rng
                    # the SAME step body the sequential fused programs run
                    # (full_step_core), so window == N sequential steps by
                    # construction; model_kwargs is None — windows exclude
                    # the per-step-kwarg features at construction
                    (params, master, opt, sstate, rng, loss, grad_norm, overflow, pre_scale) = (
                        full_step_core(params, master, opt, sstate, lr, rng, mb, None)
                    )
                    # the host lr scheduler does not advance on an
                    # overflow-skipped step; neither does the lr cursor
                    sched = jnp.where(overflow, sched, sched + 1)
                    return (params, master, opt, sstate, rng, sched), (
                        loss, grad_norm, overflow, pre_scale, rng_in,
                    )

                carry0 = (params, master, opt_state, scale_state, rng, jnp.int32(0))
                carry, ys = jax.lax.scan(one_step, carry0, stacked)
                new_params, new_master, new_opt, new_scale_state, rng, _ = carry
                losses, norms, ovfs, pre_scales, rngs_in = ys
                per_step = tuple((losses[k], norms[k], ovfs[k]) for k in range(H))
                return (
                    new_params, new_master, new_opt, new_scale_state, rng,
                    per_step, pre_scales[H - 1], rngs_in[H - 1],
                )

            window_name = f"fused_window_step_n{H}"
            if mixed:
                self._jit_fused_window_step = self._telemetry.instrument(
                    window_name,
                    fused_window_step,
                    donate_argnums=(0, 1, 2, 3),
                    out_shardings=(
                        self._param_shardings,
                        self._master_shardings,
                        self._opt_shardings,
                        None, None, None, None, None,
                    ),
                    **step_jit_extra,
                )
            else:

                def fp32_fused_window_step(master, opt_state, scale_state, lrs, rng, stacked):
                    out = fused_window_step(None, master, opt_state, scale_state, lrs, rng, stacked)
                    return out[1], out[2], out[3], out[4], out[5], out[6], out[7]

                self._jit_fused_window_step = self._telemetry.instrument(
                    window_name,
                    fp32_fused_window_step,
                    donate_argnums=(0, 1, 2),
                    out_shardings=(
                        self._master_shardings,
                        self._opt_shardings,
                        None, None, None, None, None,
                    ),
                    **step_jit_extra,
                )
        else:
            self._jit_fused_window_step = None

        if self._streamed_offload:
            # ZeRO-Infinity streamed path: the update math stays ON DEVICE —
            # offload_stats mirrors step_fn's preamble op-for-op (unscale to
            # fp32 FIRST, then overflow/norm/clip on the unscaled grads, the
            # bit-identity contract with the on-device step), then one
            # donated per-bucket program applies optimizer.apply to the
            # streamed-in master/moments slice; see _take_streamed_offload_step
            def offload_stats(grad_acc, scale):
                inv = 1.0 / (scale * gas)
                grads32 = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32) * inv, grad_acc
                )
                overflow = (
                    has_inf_or_nan(grads32) if fp16 else jnp.zeros((), jnp.bool_)
                )
                sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree_util.tree_leaves(grads32))
                grad_norm = jnp.sqrt(sq)
                if clip > 0:
                    coef = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                else:
                    coef = jnp.float32(1.0)
                return grad_norm, coef, overflow

            self._jit_offload_stats = self._telemetry.instrument("offload_stats", offload_stats)  # lint: allow(DS-R004) — read-only: the bucket programs re-read (and zero) grad_acc after
            self._jit_zero_grads = self._telemetry.instrument(
                "zero_grads",
                lambda t: jax.tree_util.tree_map(jnp.zeros_like, t),
                donate_argnums=(0,),
            )

            ho = self._host_offload
            optimizer = self.optimizer
            master_sh = jax.tree_util.tree_leaves(self._master_shardings)
            param_sh = jax.tree_util.tree_leaves(self._param_shardings)
            grad_sh = jax.tree_util.tree_leaves(self._grad_shardings)
            self._jit_offload_bucket = []
            for bi in range(ho.num_buckets):
                idx = ho.bucket_indices(bi)
                b_master_sh = tuple(master_sh[i] for i in idx)
                b_param_sh = tuple(param_sh[i] for i in idx)
                b_grad_sh = tuple(grad_sh[i] for i in idx)
                if mixed:

                    def bucket_update(masters, ms, vs, accs, params_old, scale, coef, step, lr):
                        inv = 1.0 / (scale * gas)
                        grads32 = tuple(a.astype(jnp.float32) * inv for a in accs)
                        if clip > 0:
                            grads32 = tuple(g * coef for g in grads32)
                        state = AdamState(step=step, exp_avg=tuple(ms), exp_avg_sq=tuple(vs))
                        new_master, new_state = optimizer.apply(
                            grads32, state, tuple(masters), jnp.float32(lr)
                        )
                        new_params = tuple(
                            m.astype(p.dtype) for m, p in zip(new_master, params_old)
                        )
                        zeroed = tuple(jnp.zeros_like(a) for a in accs)
                        return new_master, new_state.exp_avg, new_state.exp_avg_sq, new_params, zeroed

                    jit_fn = self._telemetry.instrument(
                        f"offload_bucket_update_b{bi}",
                        bucket_update,
                        donate_argnums=(0, 1, 2, 3, 4),
                        out_shardings=(b_master_sh, b_master_sh, b_master_sh, b_param_sh, b_grad_sh),
                        **step_jit_extra,
                    )
                else:
                    # fp32: the bucket's params ARE the master (one buffer)

                    def bucket_update(masters, ms, vs, accs, scale, coef, step, lr):
                        inv = 1.0 / (scale * gas)
                        grads32 = tuple(a.astype(jnp.float32) * inv for a in accs)
                        if clip > 0:
                            grads32 = tuple(g * coef for g in grads32)
                        state = AdamState(step=step, exp_avg=tuple(ms), exp_avg_sq=tuple(vs))
                        new_master, new_state = optimizer.apply(
                            grads32, state, tuple(masters), jnp.float32(lr)
                        )
                        zeroed = tuple(jnp.zeros_like(a) for a in accs)
                        return new_master, new_state.exp_avg, new_state.exp_avg_sq, zeroed

                    jit_fn = self._telemetry.instrument(
                        f"offload_bucket_update_b{bi}",
                        bucket_update,
                        donate_argnums=(0, 1, 2, 3),
                        out_shardings=(b_master_sh, b_master_sh, b_master_sh, b_grad_sh),
                        **step_jit_extra,
                    )
                self._jit_offload_bucket.append(jit_fn)
            self._jit_step = None
            return

        if self._host_offload is not None:
            # legacy offload path: the fused device step is replaced by (tiny
            # jitted grad stats) + host AVX Adam; see _take_model_step
            def grad_stats(grad_acc, scale):
                inv = 1.0 / (scale * gas)
                sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree_util.tree_leaves(grad_acc))
                overflow = (
                    has_inf_or_nan(grad_acc) if fp16 else jnp.zeros((), jnp.bool_)
                )
                return jnp.sqrt(sq) * inv, overflow

            self._jit_grad_stats = self._telemetry.instrument("grad_stats", grad_stats)  # lint: allow(DS-R004) — read-only: the host Adam re-reads grad_acc after
            self._jit_zero_grads = self._telemetry.instrument(
                "zero_grads",
                lambda t: jax.tree_util.tree_map(jnp.zeros_like, t),
                donate_argnums=(0,),
            )
            self._jit_reshard_params = self._telemetry.instrument(
                "reshard_params", lambda t: t, out_shardings=self._param_shardings
            )
            self._jit_step = None
            return

        # full-state donation: params, master, opt_state, grad_acc AND
        # scale_state all turn over at the step boundary, so every one is
        # donated and aliased in place by XLA instead of double-buffered
        if mixed:
            self._jit_step = self._telemetry.instrument(
                "step",
                step_fn,
                donate_argnums=(0, 1, 2, 3, 4),
                out_shardings=(
                    self._param_shardings,
                    self._master_shardings,
                    self._opt_shardings,
                    self._grad_shardings,
                    None,
                    None,
                    None,
                ),
                **step_jit_extra,
            )
        else:
            # fp32: params IS master — a single buffer; pass and return it once
            # to avoid donating the same buffer under two arguments.
            def fp32_step(master, opt_state, grad_acc, scale_state, lr):
                out = step_fn(None, master, opt_state, grad_acc, scale_state, lr)
                return out[1], out[2], out[3], out[4], out[5], out[6]

            self._jit_step = self._telemetry.instrument(
                "step",
                fp32_step,
                donate_argnums=(0, 1, 2, 3),
                out_shardings=(
                    self._master_shardings,
                    self._opt_shardings,
                    self._grad_shardings,
                    None,
                    None,
                    None,
                ),
                **step_jit_extra,
            )

    def _build_overlap_plan(self, qwz: bool, qgz: bool):
        """Comm-overlap plan for the scanned layer stack, or None.

        Requires a model family with a stacked-and-scanned ``layers`` subtree
        (TransformerLM-style), no ZeRO++ wire-format override (qwZ/qgZ own
        their gather/reduce schedules), and no host-offloaded optimizer (the
        host Adam re-reads the accumulation buffer, so the in-loop scatter
        stays with the stock schedule)."""
        if qwz or qgz or self._host_offload is not None:
            return None
        params = self._params
        if not (isinstance(params, dict) and isinstance(params.get("layers"), dict)):
            return None
        mcfg = getattr(self.module, "config", None)
        if not getattr(mcfg, "scan_layers", False):
            return None
        from deepspeed_tpu.runtime.zero.overlap import build_overlap_plan

        stacked = params["layers"]
        num_layers = int(jax.tree_util.tree_leaves(stacked)[0].shape[0])
        plan = build_overlap_plan(
            self._config.zero_config,
            self.topology,
            stacked,
            self._param_specs["layers"],
            self._grad_specs["layers"],
            num_layers,
            # a2a-stage wire format: the MoE model family's knob rides the
            # plan so the layer reads one source of truth while tracing
            moe_quantized_a2a=getattr(mcfg, "moe_quantized_a2a", None),
        )
        if plan is not None and plan.prefetch_enabled and (
            self.progressive_layer_drop is not None
            or self.random_ltd_scheduler is not None
        ):
            # PLD/random-LTD restructure the layer loop themselves (cond-
            # skipped layers / token-subset segments) — the prefetch
            # pipeline does not run there. Disable it VISIBLY rather than
            # letting prefetch_enabled=True report a pipeline that never
            # engaged; the bucketed in-scan grad reduction still applies.
            log_dist(
                "zero.prefetch_layers is a no-op under progressive_layer_drop/"
                "random_ltd (the layer loop is theirs); pipelined gather "
                "disabled, bucketed grad reduce-scatter stays on",
                ranks=[0],
            )
            plan.prefetch_enabled = False
            plan.depth = 0
            if not plan.reduce_enabled and not plan.a2a_enabled:
                plan = None
        return plan

    def _overlap_compiler_options(self) -> Optional[Dict[str, Any]]:
        """XLA latency-hiding-scheduler options for the step-flavor programs.

        The pipeline/bucketing create the independent work; this scheduler
        makes XLA interleave it with the collective DMAs. TPU-only (the CPU
        mesh has no async collectives to schedule) and best-effort: the
        telemetry wrapper drops ``compiler_options`` on a jax whose ``jit``
        predates them."""
        try:
            platform = jax.devices()[0].platform
        except Exception:
            return None
        if platform != "tpu":
            return None
        if self._overlap_plan is None and not self._config.zero_config.overlap_comm:
            return None
        return {"xla_tpu_enable_latency_hiding_scheduler": "true"}

    # ------------------------------------------------------------------
    # train loop API (reference parity)
    # ------------------------------------------------------------------
    def __call__(self, batch):
        return self.forward(batch)

    def forward(self, batch):
        if self._window_stash:
            raise RuntimeError(
                "forward() called with a multi-step window mid-flight: "
                f"{len(self._window_stash)} computed step(s) are uncommitted; "
                "drive them through train_batch(data_iter) first"
            )
        if not self._initialized:
            self.init_params(batch)
        self.timers(FORWARD_GLOBAL_TIMER).start()
        if self._training_mode:
            # eval forwards must not open/extend a throughput window
            self.tput_timer.start()
        if self.curriculum_scheduler is not None and self._training_mode:
            seqlen = self.curriculum_scheduler.update_difficulty(self.global_steps + 1)
            batch = _truncate_seq(batch, seqlen)
        with self.tracer.span("train.h2d"):
            placed = self._place_batch(batch)
        if self._param_stream is not None:
            loss = self._stream_forward(placed)
            self.timers(FORWARD_GLOBAL_TIMER).stop(sync=False)
            return loss
        fused_train = self._training_mode and self._fused_step_enabled
        if not fused_train:
            self._rng, step_rng = jax.random.split(self._rng)
        profiling = (
            self.flops_profiler is not None
            and self.global_steps == self._config.flops_profiler_config.profile_step
            and self._training_mode
            # only the first microbatch of the profile step (global_steps is
            # constant across a gradient-accumulation window)
            and self.micro_steps % self.gradient_accumulation_steps() == 0
        )
        if profiling:
            self.flops_profiler.start_profile()
        if fused_train:
            if self._pending_commit is not None:
                raise RuntimeError(
                    "forward() called again before step(): with "
                    "gradient_accumulation_steps=1 the engine fuses the "
                    "optimizer update into the forward program, so every "
                    "training forward must be followed by backward()+step()"
                )
            lr = self.optimizer.param_groups[0]["lr"]
            # kwargs FIRST (may split self._rng for LTD index sampling), so
            # parent_rng is exactly the rng the fused step receives — the
            # debug-grad recompute derives its dropout key from it
            model_kwargs = self._model_kwargs(placed)
            parent_rng = self._rng
            if self.mixed_precision:
                fwd_args = (
                    self._params, self._master, self._opt_state,
                    self._scale_state, lr, self._rng, placed, model_kwargs,
                )
            else:
                fwd_args = (
                    self._master, self._opt_state, self._scale_state, lr, self._rng, placed,
                    model_kwargs,
                )
            if profiling:
                self._last_profile_args = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                    if hasattr(x, "shape")
                    else x,
                    fwd_args,
                )
                self._profile_fn = self._jit_fused_step
            # dispatch ENQUEUE only: jit returns futures; device time shows
            # up at the next blocking fetch, never as a sync here
            with self.tracer.span("train.dispatch", program="fused_step"):
                out = self._jit_fused_step(*fwd_args)
            # the inputs were donated — adopt the new state immediately so the
            # engine never holds references to deleted buffers
            if self.mixed_precision:
                loss, self._params, self._master, self._opt_state, self._scale_state, norm, ovf, pre_scale, self._rng = out
            else:
                loss, self._master, self._opt_state, self._scale_state, norm, ovf, pre_scale, self._rng = out
                self._params = self._master
            self._pending_commit = (norm, ovf)
            # host-side batch reference only (no HBM pin) for the on-demand
            # debug-grad surface (get_last_grads); scale_state is donated, so
            # the exact scale the step consumed comes back as a program
            # OUTPUT (pre_scale) — it survives the dynamic-loss-scale update
            self._last_batch = batch
            self._last_fwd_rng = parent_rng
            # the exact kwargs the step consumed (LTD indices included) — the
            # debug-grad surface must NOT resample them
            self._last_model_kwargs = model_kwargs
            self._last_fwd_scale = pre_scale
            self._last_loss = loss
            self._in_forward = True
        elif self._training_mode:
            if self._grad_acc is None:
                # fuse_grad_accum engages only through train_batch(); a
                # caller driving per-microbatch forward/backward/step falls
                # back to the unfused programs (and pays per-microbatch
                # dispatch again), which need the accumulation buffer
                if self._fused_accum_enabled and not getattr(self, "_warned_unfused_fallback", False):
                    self._warned_unfused_fallback = True
                    logger.warning(
                        "fuse_grad_accum is on but forward() is being driven "
                        "per microbatch; the single-dispatch fused step only "
                        "engages through train_batch() — falling back to the "
                        "unfused per-microbatch programs"
                    )
                self._grad_acc = self._alloc_grad_acc()
            fwd_args = (
                self._params, self._grad_acc, self._scale_state.scale, step_rng, placed,
                self._model_kwargs(placed),
            )
            if profiling:
                # abstract shapes only: grad_acc is donated by the call below
                self._last_profile_args = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                    if hasattr(x, "shape")
                    else x,
                    fwd_args,
                )
                self._profile_fn = self._jit_fwd_bwd
            # one grad-accum microstep (fwd+bwd+accumulate enqueue)
            micro_idx = self.micro_steps % self.gradient_accumulation_steps()
            with self.tracer.span("train.microstep", micro=micro_idx):
                loss, self._grad_acc = self._jit_fwd_bwd(*fwd_args)
            self._last_loss = loss
            self._in_forward = True
        else:
            with self.tracer.span("eval.dispatch"):
                loss = self._jit_eval(self._params, step_rng, placed)
            self._last_loss = loss
        if profiling:
            jax.device_get(loss)  # close the latency window at step end
            pcfg = self._config.flops_profiler_config
            self.flops_profiler.stop_profile()
            self.flops_profiler.print_model_profile(
                profile_step=pcfg.profile_step,
                module_depth=pcfg.module_depth,
                top_modules=pcfg.top_modules,
                detailed=pcfg.detailed,
                output_file=pcfg.output_file,
            )
            self.flops_profiler.end_profile()
            self._last_profile_args = None
        self.timers(FORWARD_GLOBAL_TIMER).stop(sync=False)
        return loss

    def _stream_forward(self, placed):
        """Forward on the layer-streamed param-offload path. Returns the
        (unscaled) loss; the streamer stashes activations for backward()."""
        from deepspeed_tpu.models.transformer import _split_batch

        if self.progressive_layer_drop is not None or self.random_ltd_scheduler is not None:
            raise NotImplementedError(
                "progressive_layer_drop / random_ltd are unsupported on the "
                "param-offload path (the layer streamer replays a fixed "
                "layer sequence)"
            )
        tokens, labels = _split_batch(placed)
        if not self._training_mode:
            # labels=None → logits (inference head); else eval loss
            out = self._param_stream.eval_forward(tokens, labels)
            if labels is not None:
                self._last_loss = out
            return out
        if labels is None:
            raise ValueError(
                "param-offload training expects (tokens, labels) batches "
                "(dict with input_ids/labels, or a 2-tuple)"
            )
        if self._in_forward:
            raise RuntimeError(
                "forward() called again before backward() on the param-offload "
                "path: each microbatch's gradients are produced by backward(), "
                "so every training forward must complete backward() first"
            )
        scale = float(jax.device_get(self._scale_state.scale))
        self._rng, sub = jax.random.split(self._rng)
        loss = self._param_stream.forward(tokens, labels, sub, scale) / scale
        self._stream_scale = scale
        self._in_forward = True
        self._last_loss = loss
        return loss

    def backward(self, loss, retain_graph: bool = False, scale_wrt_gas: bool = True):  # noqa: ARG002
        """Gradients were produced (fused) in ``forward``; this validates the
        call protocol and is where the reference reduces at GAS boundaries —
        here the reduction is part of the jitted step's grad shardings.
        On the param-offload path this runs the real layer-streamed backward."""
        if not self._training_mode:
            raise RuntimeError("backward() called in eval mode")
        if not self._in_forward:
            raise RuntimeError("backward() called before forward()")
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        if self._param_stream is not None:
            self._param_stream.backward(self._stream_scale)
        self._in_forward = False
        self.timers(BACKWARD_GLOBAL_TIMER).stop(sync=False)
        return loss

    def step(self, lr_kwargs=None):  # noqa: ARG002
        self.timers(STEP_GLOBAL_TIMER).start()
        boundary = self.is_gradient_accumulation_boundary()
        if boundary:
            # counted BEFORE the commit so the monitor feed (which runs in
            # the commit's bookkeeping tail) reports this step inclusively
            self.metrics.counter("train.steps").inc()
            with self.tracer.span("train.step_commit"):
                self._take_model_step()
        self.micro_steps += 1
        self.global_samples += self.train_micro_batch_size_per_gpu() * self.data_parallel_world_size()
        self.timers(STEP_GLOBAL_TIMER).stop(sync=False)
        self.tput_timer.stop(global_step=boundary)

    def _apply_mics_mesh(self) -> None:
        """Map zero_optimization.mics_shard_size onto the mesh's MiCS split:
        ZeRO shards within groups of that size (the 'data' axis) and
        replicates across groups ('data_outer')."""
        mics = self._config.zero_config.mics_shard_size
        if mics is None or mics <= 0:
            return
        from deepspeed_tpu.runtime.config import split_data_axis

        mc = self._config.mesh_config
        split_data_axis(mc, mics, len(jax.devices()), "mics_shard_size")
        log_dist(
            f"MiCS: ZeRO shard groups of {mics} rank(s) "
            f"(data {mc.data} × expert {mc.expert} × sequence {mc.sequence}), "
            f"replicated over {mc.data_outer} groups",
            ranks=[0],
        )

    def _validate_multi_step(self) -> None:
        """Reject configs a multi-step training window cannot honor
        bit-identically (ISSUE 14). Each of these features injects per-step
        host decisions between optimizer steps — exactly what the fused
        window removes — so arming both is a contradiction, not a fallback:

        * ``fuse_grad_accum`` off at gas>1: the window scans the fused
          grad-accum step body; its sequential fallback steps must run the
          same program family or the mixed run stops being bit-exact.
        * curriculum learning (per-step sequence-shape schedule), PLD and
          random-LTD (per-step traced kwargs / host-sampled index shapes),
          MoQ (host re-quantizes params between steps).
        * qgZ / offloaded optimizer or params (their step paths are
          unfused by construction).
        * an lr scheduler without ``state_dict``/``load_state_dict``: the
          window pre-evaluates the schedule by snapshot→replay→restore.
        """
        ms = self._config.compile_config.multi_step
        if not ms.enable:
            return
        if jax.process_count() > 1:
            # the window former stages PRE-PLACED batches and stacks them
            # device-side; a multi-process global batch cannot be re-stacked
            # across hosts (same constraint fuse_grad_accum documents for
            # pre-placed inputs) — reject up front with the right name
            # instead of dying inside _place_stacked_batch mid-training
            raise NotImplementedError(
                "compile.multi_step currently requires a single-process run "
                "(the window former stacks device-placed microbatches, which "
                "multi-process global arrays do not support); disable "
                "multi_step on multi-host launches"
            )
        if self.gradient_accumulation_steps() > 1 and not self._config.compile_config.fuse_grad_accum:
            raise ValueError(
                "compile.multi_step with gradient_accumulation_steps > 1 "
                "requires compile.fuse_grad_accum (the window scans the "
                "fused grad-accum step body)"
            )
        cl_cfg = self._config.curriculum_learning_config
        if cl_cfg and cl_cfg.get("enabled", False):
            raise ValueError(
                "compile.multi_step is incompatible with curriculum_learning "
                "(the per-step seqlen schedule changes batch shapes inside "
                "the window)"
            )
        if self.progressive_layer_drop is not None:
            raise ValueError(
                "compile.multi_step is incompatible with "
                "progressive_layer_drop (theta is a per-step host kwarg)"
            )
        if self.random_ltd_scheduler is not None:
            raise ValueError(
                "compile.multi_step is incompatible with random_ltd "
                "(per-step host-sampled index shapes retrace the program)"
            )
        if self.quantizer is not None:
            raise ValueError(
                "compile.multi_step is incompatible with MoQ (the host "
                "re-quantizes the compute store between optimizer steps)"
            )
        zcfg = self._config.zero_config
        if zcfg.zero_quantized_gradients:
            raise ValueError(
                "compile.multi_step is incompatible with "
                "zero_quantized_gradients (the qgZ grad path is unfused)"
            )
        off = zcfg.offload_optimizer
        streamed_opt_offload = (
            off is not None
            and self._offload_requested(off)
            and off.pipeline
            and str(off.device.value) == "cpu"
        )
        if self._offload_requested(zcfg.offload_param):
            raise ValueError(
                "compile.multi_step is incompatible with offload_param "
                "(the layer stream owns the per-microbatch update loop)"
            )
        if self._offload_requested(off) and not streamed_opt_offload:
            raise ValueError(
                "compile.multi_step is incompatible with the LEGACY host-Adam "
                "offload (the host owns that update loop); the streamed "
                "ZeRO-Infinity path (offload_optimizer.device=cpu with "
                "pipeline_read/pipeline_write) composes with windows"
            )
        if self.lr_scheduler is not None and not (
            hasattr(self.lr_scheduler, "state_dict")
            and hasattr(self.lr_scheduler, "load_state_dict")
        ):
            raise ValueError(
                "compile.multi_step requires an lr scheduler with "
                "state_dict/load_state_dict (the window pre-evaluates the "
                "schedule via snapshot -> replay -> restore)"
            )

    def _validate_zeropp_config(self) -> None:
        """Consume the ZeRO++ keys (reference zero/config.py:260-272) or
        reject them loudly — an accepted-but-ignored scaling flag is worse
        than an error."""
        z = self._config.zero_config
        stage3 = int(z.stage) >= 3
        if z.zero_quantized_nontrainable_weights:
            raise NotImplementedError(
                "zero_quantized_nontrainable_weights is not implemented (the "
                "engine does not track per-param trainability); unset it or "
                "use zero_quantized_weights"
            )
        if z.zero_quantized_weights and not stage3:
            raise ValueError("zero_quantized_weights (qwZ) requires ZeRO stage 3")
        if z.zero_quantized_gradients and not stage3:
            raise ValueError("zero_quantized_gradients (qgZ) requires ZeRO stage 3")
        if int(z.zero_hpz_partition_size or 1) > 1:
            if not stage3:
                raise ValueError("zero_hpz_partition_size (hpZ) requires ZeRO stage 3")
            if not (self._config.bfloat16_enabled or self._config.fp16_enabled):
                raise ValueError(
                    "zero_hpz_partition_size (hpZ) requires bf16/fp16 training: "
                    "the secondary partition is a second, compute-dtype param "
                    "copy — fp32 training keeps a single master copy"
                )
            from deepspeed_tpu.runtime.zero.zeropp import apply_hpz_mesh

            apply_hpz_mesh(self._config.mesh_config, z, len(jax.devices()))

    def _offload_enabled(self) -> bool:
        requested = self._offload_requested(self._config.zero_config.offload_optimizer)
        if requested and self._config.zero_optimization_stage < 1:
            raise ValueError(
                "offload_optimizer requires ZeRO stage >= 1 (stage 0 keeps full "
                "optimizer state on device; set zero_optimization.stage)"
            )
        return requested

    @staticmethod
    def _offload_requested(off) -> bool:
        return off is not None and str(off.device) not in ("none", "OffloadDeviceEnum.none")

    def _validate_host_adam(self, feature: str) -> None:
        """Both offload paths run the native host Adam/AdamW; they own the
        update rule, so the configured optimizer must be an adam variant and
        there can be no client optimizer."""
        opt_cfg = self._config.optimizer_config
        opt_type = opt_cfg.type.lower() if opt_cfg is not None and opt_cfg.type else C.ADAM_OPTIMIZER
        if opt_type not in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER, C.FUSED_ADAM_OPTIMIZER, C.CPU_ADAM_OPTIMIZER):
            raise ValueError(
                f"{feature} runs the host Adam/AdamW (DeepSpeedCPUAdam analog); "
                f"configured optimizer {opt_type!r} is unsupported — use an adam "
                f"variant or disable {feature}"
            )
        if self.client_optimizer is not None:
            raise ValueError(
                f"{feature} is incompatible with a client optimizer: the host "
                "offload path owns the update rule (Adam/AdamW)"
            )

    def _param_offload_enabled(self) -> bool:
        requested = self._offload_requested(self._config.zero_config.offload_param)
        if requested and self._config.zero_optimization_stage != 3:
            raise ValueError(
                "offload_param requires ZeRO stage 3 (set zero_optimization.stage=3); "
                f"got stage {self._config.zero_optimization_stage}"
            )
        return requested

    def _init_param_stream(self, batch: Any) -> None:
        """ZeRO-Infinity parameter offload: the model's layers live in host
        DRAM or on local SSD and stream through HBM one layer at a time
        (``runtime/zero/param_offload.py``; reference:
        ``deepspeed/runtime/zero/stage3.py:542`` tensor swapping +
        ``partitioned_param_swapper.py:36``). Replaces the jitted monolithic
        step — model size is bounded by host memory, not HBM."""
        from deepspeed_tpu.runtime.zero.param_offload import ParamStreamEngine

        opt_cfg = self._config.optimizer_config
        self._validate_host_adam("offload_param")
        sharded_axes = {
            ax: self.topology.axis_size(ax)
            for ax in ("model", "sequence", "pipe", "expert")
            if self.topology.axis_size(ax) > 1
        }
        if sharded_axes:
            raise ValueError(
                "offload_param layer streaming currently supports pure data "
                f"parallelism; mesh has non-trivial axes {sharded_axes} whose "
                "shardings it would silently drop (streamed layers are "
                "replicated per chip)"
            )
        if self._pending_model_parameters is not None:
            params = self._pending_model_parameters
        else:
            # init params on the host when a cpu backend exists (the whole
            # point is that the model may not fit in HBM)
            try:
                host = jax.local_devices(backend="cpu")[0]
            except RuntimeError:
                host = None
            if host is not None:
                with jax.default_device(host):
                    params = self.module.init(self._rng, batch)
            else:
                params = self.module.init(self._rng, batch)
        self._param_stream = ParamStreamEngine(
            self.module,
            params,
            self.topology,
            self._config.zero_config,
            dict(opt_cfg.params) if opt_cfg is not None else {},
            self.compute_dtype,
            fp16=self._config.fp16_enabled,
            act_offload=self._config.activation_checkpointing_config.cpu_checkpointing,
        )
        del params
        self._pending_model_parameters = None
        self._scale_state = jax.device_put(self.loss_scaler.init_state())
        self._fused_step_enabled = False
        self._initialized = True
        log_dist(
            f"Initialized param-offload state: {self._param_stream.num_parameters():,} parameters",
            ranks=[0],
        )

    def _take_offload_step(self, lr: float) -> None:
        """Host-optimizer step (ZeRO-Offload): device computes grad stats,
        the native AVX Adam updates host partitions, params return to chip."""
        scale = self._scale_state.scale
        grad_norm, overflow_flag = self._jit_grad_stats(self._grad_acc, scale)
        self._last_grad_norm = grad_norm
        overflow = bool(jax.device_get(overflow_flag)) if self._config.fp16_enabled else False
        if not overflow:
            clip = self._config.gradient_clipping
            norm = float(jax.device_get(grad_norm))
            clip_coef = min(1.0, clip / (norm + 1e-6)) if clip > 0 else 1.0
            inv = 1.0 / (float(jax.device_get(scale)) * self._gas_divisor)
            grad_leaves = jax.tree_util.tree_leaves(self._grad_acc)
            new_leaves = self._host_offload.step(grad_leaves, lr, inv, clip_coef)
            new_params = self._host_offload.unflatten(new_leaves)
            # restore the engine's param shardings (master shards may be
            # finer, e.g. persistent small params replicated under stage 3)
            self._params = self._jit_reshard_params(new_params)
        self._grad_acc = self._jit_zero_grads(self._grad_acc)
        self._scale_state = self.loss_scaler.update(self._scale_state, overflow_flag)
        self._overflow = overflow

    def _take_streamed_offload_step(self, lr: float) -> None:
        """ZeRO-Infinity streamed step (runtime/zero/host_offload.py): host
        master/moments stream device-ward bucket by bucket through the
        depth-2 pipeline, each donated bucket program applies the EXACT
        on-device update math, and the updated slice streams back D2H while
        the next bucket computes. fp16 overflow discards the staged uploads
        and skips the bucket loop entirely — bit-identical to the fused
        path's where-revert (everything keeps its pre-step value) without
        paying the stream."""
        ho = self._host_offload
        nb = ho.num_buckets
        # prime the double buffer: buckets 0 and 1 ride behind the backward
        # still executing on the device stream
        with self.tracer.span("train.offload_h2d", buckets=min(2, nb)):
            ho.h2d_bucket(0)
            if nb > 1:
                ho.h2d_bucket(1)
        scale = self._scale_state.scale
        grad_norm, coef, overflow_flag = self._jit_offload_stats(self._grad_acc, scale)
        self._last_grad_norm = grad_norm
        overflow = bool(jax.device_get(overflow_flag)) if self._config.fp16_enabled else False
        if overflow:
            ho.discard_staged()
            self._grad_acc = self._jit_zero_grads(self._grad_acc)
        else:
            acc_leaves = jax.tree_util.tree_leaves(self._grad_acc)
            param_leaves = jax.tree_util.tree_leaves(self._params)
            new_params = list(param_leaves)
            new_acc = list(acc_leaves)
            step = np.int32(ho.step_count)
            for bi in range(nb):
                idx = ho.bucket_indices(bi)
                masters, ms, vs = ho.take_staged(bi)
                accs = tuple(acc_leaves[i] for i in idx)
                if self.mixed_precision:
                    p_old = tuple(param_leaves[i] for i in idx)
                    nm, nmm, nmv, np_b, za = self._jit_offload_bucket[bi](
                        tuple(masters), tuple(ms), tuple(vs), accs, p_old, scale, coef, step, lr
                    )
                else:
                    # fp32: the live params ARE the master slice
                    p_old = tuple(param_leaves[i] for i in idx)
                    nm, nmm, nmv, za = self._jit_offload_bucket[bi](
                        p_old, tuple(ms), tuple(vs), accs, scale, coef, step, lr
                    )
                    np_b = nm
                for k, i in enumerate(idx):
                    new_params[i] = np_b[k]
                    new_acc[i] = za[k]
                if bi + 2 < nb:
                    with self.tracer.span("train.offload_h2d", buckets=1):
                        ho.h2d_bucket(bi + 2)
                chaos.point("train.mid_offload_stream", bucket=bi)
                with self.tracer.span("train.offload_d2h", bucket=bi):
                    ho.d2h_bucket(bi, nm, nmm, nmv)
                    ho.materialize_writes(keep=1)
            self._params = ho.unflatten(new_params)
            self._grad_acc = ho.unflatten(new_acc)
            ho.step_count += 1
        ho.note_step()
        self._scale_state = self.loss_scaler.update(self._scale_state, overflow_flag)
        self._overflow = overflow

    def _finish_step_bookkeeping(self, overflow_flag) -> None:
        """Post-update host tail shared by every step flavor: counters,
        fp16 overflow accounting (the only host-visible sync, and only under
        fp16), lr scheduler, monitor."""
        # the classic preemption instant: device state updated, nothing of
        # the step committed host-side yet
        chaos.point("train.mid_step")
        self.global_steps += 1
        if self._config.fp16_enabled and overflow_flag is not None:
            self._overflow = (
                overflow_flag
                if isinstance(overflow_flag, bool)
                else bool(jax.device_get(overflow_flag))
            )
        if self._overflow:
            self.skipped_steps += 1
            log_dist(
                f"[deepspeed_tpu] OVERFLOW! skipping step, new loss scale: {self.loss_scale}",
                ranks=[0],
            )
        if self.lr_scheduler is not None and not self._overflow:
            self.lr_scheduler.step()
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if self.random_ltd_scheduler is not None:
            self.random_ltd_scheduler.update(self.global_steps)
        step_was_skipped = self._overflow
        self._overflow = False
        if self.quantizer is not None and self._params is not None and not step_was_skipped:
            # MoQ: re-quantize the compute-dtype store after the update; the
            # fp32 master stays full precision (reference fp16 optimizer
            # calls Quantizer.quantize after each step)
            if self.quantizer.out_shardings is None:
                self.quantizer.out_shardings = self._param_shardings
            self._params = self.quantizer.quantize_tree(self._params, self.global_steps)
        # interval auto-save (checkpoint.interval_steps + save_dir): the
        # preemption-survival loop — with async_snapshot on, the step only
        # pays the device->host snapshot
        ccfg = self._config.checkpoint_config
        if ccfg.save_dir and ccfg.interval_steps > 0 and self.global_steps % ccfg.interval_steps == 0:
            self.save_checkpoint(ccfg.save_dir)
        if self.monitor is not None:
            interval = (
                self._config.monitor_config.interval_steps
                or self._config.steps_per_print
            )
            if self.global_steps % interval == 0:
                self._write_monitor()

    def _take_model_step(self) -> None:
        if self._fused_step_enabled:
            if self._pending_commit is None:
                raise RuntimeError("step() called with no pending forward()")
            self._last_grad_norm, overflow_flag = self._pending_commit
            self._pending_commit = None
            self._finish_step_bookkeeping(overflow_flag)
            return
        lr = self.optimizer.param_groups[0]["lr"]
        if self._param_stream is not None:
            grad_norm, overflow = self._param_stream.step(
                lr,
                float(jax.device_get(self._scale_state.scale)),
                self._config.gradient_clipping,
            )
            self._last_grad_norm = jnp.float32(grad_norm)
            self._scale_state = self.loss_scaler.update(
                self._scale_state, jnp.asarray(overflow)
            )
            self._overflow = overflow
            self._finish_step_bookkeeping(overflow)
            return
        if self._host_offload is not None:
            if self._streamed_offload:
                self._take_streamed_offload_step(lr)  # sets self._overflow itself
            else:
                self._take_offload_step(lr)  # sets self._overflow itself
            self._finish_step_bookkeeping(self._overflow)
            return
        if self.mixed_precision:
            (
                self._params,
                self._master,
                self._opt_state,
                self._grad_acc,
                self._scale_state,
                self._last_grad_norm,
                overflow_flag,
            ) = self._jit_step(
                self._params, self._master, self._opt_state, self._grad_acc, self._scale_state, lr
            )
        else:
            (
                self._master,
                self._opt_state,
                self._grad_acc,
                self._scale_state,
                self._last_grad_norm,
                overflow_flag,
            ) = self._jit_step(self._master, self._opt_state, self._grad_acc, self._scale_state, lr)
            self._params = self._master
        self._finish_step_bookkeeping(overflow_flag)

    def _write_monitor(self) -> None:
        events = [
            ("Train/Samples/lr", self.optimizer.param_groups[0]["lr"], self.global_samples),
        ]
        if self._last_loss is not None:
            events.append(("Train/Samples/train_loss", float(jax.device_get(self._last_loss)), self.global_samples))
        totals = self._telemetry.totals()
        events.append(("Train/Samples/compile_count", float(totals["compiles"]), self.global_samples))
        events.append(("Train/Samples/compile_seconds", float(totals["compile_seconds"]), self.global_samples))
        # periodic metric feed from the observability hub: step-phase means
        # off the timeline plus every registered counter/gauge/histogram
        events.extend(self._obs_hub.monitor_events(self.global_samples))
        self.monitor.write_events(events)

    def observability(self, analysis: bool = True) -> Dict[str, Any]:
        """The merged observability report (ISSUE 10): the live step-phase
        ``timeline`` (span counts, per-phase ms aggregates, ring-buffer
        state) and ``metrics`` (counters/gauges/histograms incl. p50/p99)
        next to the engine's existing surfaces — ``compile``
        (``compile_stats()``), ``analysis`` (``analysis_report()``; pass
        ``analysis=False`` to skip its re-trace/re-compile cost), and
        ``checkpoint`` (``checkpoint_stats()``). The hub behind it also
        exports the timeline as a Perfetto/Chrome trace
        (``engine.observability_hub.export_chrome_trace(path)``) and owns
        the crash flight recorder (``tracing.flight_recorder``)."""
        return self._obs_hub.report(exclude=() if analysis else ("analysis",))

    @property
    def observability_hub(self) -> ObservabilityHub:
        return self._obs_hub

    def compile_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-program compile telemetry snapshot: for each jitted program
        (fwd_bwd, step, fused_step, fused_accum_step, eval_fwd, ...) the
        trace count, compile count (trace-triggering dispatches), total
        dispatch count, wall time spent in compiling dispatches, and
        explicit invalidations. The steady-state contract: with
        fuse_grad_accum on and gas>1, ``fused_accum_step`` shows exactly one
        dispatch per optimizer step and one compile total; the unfused path
        shows gas ``fwd_bwd`` dispatches + one ``step`` per optimizer step."""
        return self._telemetry.stats()

    def analysis_report(self, programs=None, passes=None) -> Dict[str, Any]:
        """Static-analysis report over every dispatched engine program (or
        the named subset): per program, the donation-aliasing, dtype-
        promotion, host-transfer, and collective-schedule pass results plus
        retrace-cause diffs; ``totals`` aggregates violation counts, a
        ``donation_verified`` flag, and the static per-device collective
        bytes the bench records track. Sits next to ``compile_stats()`` —
        same registry, compile-time truth instead of runtime counters.
        Re-traces and re-compiles each analyzed program once (abstract
        shapes only: no device buffers are touched)."""
        from deepspeed_tpu.analysis import engine_analysis_report

        return engine_analysis_report(
            self._telemetry,
            self._config.analysis_config,
            programs=programs,
            passes=passes,
            extra_config=self._analysis_extra_config(),
        )

    def _analysis_extra_config(self) -> Optional[Dict[str, Any]]:
        """Engine-declared analysis-pass inputs: the streamed-offload engine
        hands the overlap pass its H2D/D2H stream schedule so the pass can
        account (and gate) the declared transfers next to the collectives."""
        if self._streamed_offload and self._host_offload is not None:
            return {"offload_stream": self._host_offload.stream_schedule()}
        return None

    def _verify_program_static(self, name: str) -> None:
        """analysis.verify hook: passes over one freshly compiled program."""
        from deepspeed_tpu.analysis import verify_program

        verify_program(
            self._telemetry,
            self._config.analysis_config,
            name,
            logger=logger,
            extra_config=self._analysis_extra_config(),
        )

    def memory_report(
        self, include_programs: bool = False, enforce: bool = True
    ) -> Dict[str, Any]:
        """Static per-chip HBM residency ledger over the engine's live
        persistent state: compute params, fp32 master (skipped when it IS
        the param tree), optimizer state, gradient-accumulation buffers,
        loss-scale state — each with global/per-chip/replicated byte
        accounting from its sharding — plus, on the offload paths, the
        host-resident master/moments and the streamed path's ≤ 2-bucket
        device staging bound. ``include_programs=True`` folds in the
        per-program transient peak estimates from the analysis memory pass
        (re-traces each program once). ``enforce=True`` (the default for
        direct calls) applies ``analysis.hbm_budget_bytes``: over budget
        raises :class:`~deepspeed_tpu.analysis.HbmBudgetError` (or warns,
        per ``analysis.hbm_budget``) with per-buffer attribution; the
        observability hub reads with ``enforce=False``."""
        from deepspeed_tpu.analysis import MemoryLedger

        acfg = self._config.analysis_config
        ledger = MemoryLedger(
            hbm_budget_bytes=acfg.hbm_budget_bytes, mode=acfg.hbm_budget
        )
        if self._params is not None:
            ledger.add_tree("params", self._params, kind="params")
        if self._master is not None and self._master is not self._params:
            ledger.add_tree("master", self._master, kind="optimizer")
        if self._opt_state is not None:
            ledger.add_tree("opt_state", self._opt_state, kind="optimizer")
        if self._grad_acc is not None:
            ledger.add_tree("grad_acc", self._grad_acc, kind="grads")
        if self._scale_state is not None:
            ledger.add_tree("scale_state", self._scale_state, kind="scaler")
        ho = self._host_offload
        if ho is not None and self._streamed_offload:
            rep = ho.memory_report()
            ledger.add_persistent(
                "offload_host_state",
                per_chip_bytes=rep["host_bytes"],
                location="host",
                kind="optimizer",
                detail=rep,
            )
            # the streamed path's whole device-side optimizer footprint:
            # the static ≤ 2-bucket staging bound, NOT the model-sized state
            ledger.add_persistent(
                "offload_device_buckets",
                per_chip_bytes=rep["device_residency_bound_bytes"],
                kind="offload_buckets",
                detail={
                    "buckets": rep["buckets"],
                    "max_bucket_bytes": rep["max_bucket_bytes"],
                    "staged_bytes": rep["staged_bytes"],
                    "pending_bytes": rep["pending_bytes"],
                },
            )
        elif ho is not None:
            # legacy ZeRO-Offload (host AVX Adam): master + moments in DRAM
            try:
                host = 3 * sum(
                    int(sh.master.nbytes)
                    for shards in ho._shards
                    for sh in shards
                )
            except Exception:
                host = 0
            ledger.add_persistent(
                "offload_host_state",
                per_chip_bytes=host,
                location="host",
                kind="optimizer",
            )
        if include_programs:
            try:
                rep = self.analysis_report(passes=["memory"])
                for pname, entry in rep.get("programs", {}).items():
                    est = (
                        entry.get("passes", {})
                        .get("memory", {})
                        .get("summary", {})
                        .get("estimate")
                    )
                    if est:
                        ledger.add_program(pname, est)
            except Exception as e:  # analysis failure ≠ ledger failure
                logger.warning(f"memory ledger: program estimates failed: {e}")
        if enforce:
            return ledger.enforce(logger=logger)
        return ledger.report()

    def train_batch(self, data_iter=None, batch=None):
        """Convenience: run a full GAS cycle — gas × fwd/bwd + step, or,
        with ``compile.fuse_grad_accum`` on, ONE fused jitted program for
        the whole optimizer step. With ``compile.multi_step`` armed and an
        iterator supplied, the engine additionally forms N-step fused
        WINDOWS: one call dispatches ``horizon`` full optimizer steps in a
        single program, and the following N-1 calls commit the remaining
        (already computed) steps without touching the device — the
        training loop's step count and per-step losses are unchanged,
        bit-identical to the unwindowed run. Armed calls return the step's
        loss as a 0-d device array (``float()`` it to force a fetch); the
        host-side values flow through the deferred loss drain
        (``drained_losses()``) instead of a blocking per-step
        ``device_get``.

        ``batch``, when given, is the FULL-step batch — its leading dim is
        sliced into ``gas`` microbatches (matching the pipeline engine's
        contract so the same caller works at any mesh.pipe)."""
        gas = self.gradient_accumulation_steps()
        if self._window_stash:
            if batch is not None:
                raise RuntimeError(
                    "train_batch(batch=...) called with a multi-step window "
                    "mid-flight: the window's remaining steps already consumed "
                    "their data; keep driving with train_batch(data_iter)"
                )
            return self._commit_window_step()
        if (
            self._window_armed
            and data_iter is not None
            and batch is None
            and self._training_mode
            and self._initialized
            and not self._in_forward
            and self._pending_commit is None
            and self._param_stream is None
        ):
            out = self._try_train_window(data_iter)
            if out is not _NO_WINDOW:
                return out
        if batch is not None:
            micro = self._split_step_batch(batch, gas)
        else:
            # an armed engine keeps pulling through its prefetching wrapper
            # even on sequential-fallback steps, so window-pulled batches
            # are never dropped and the staged h2d stays warm
            src = (
                self._window_loader(data_iter)
                if (self._window_armed and self._training_mode and data_iter is not None)
                else data_iter
            )
            with self.tracer.span("train.data_fetch", gas=gas):
                micro = [next(src) for _ in range(gas)]
        if not self._initialized:
            self.init_params(micro[0])
        if (
            self._fused_accum_enabled
            and self._training_mode
            and not self._in_forward
            and self._pending_commit is None
            and self._param_stream is None
            and self.micro_steps % gas == 0
            # the flops profiler hooks the per-microbatch programs; give it
            # the unfused window it expects on its profile step
            and not (
                self.flops_profiler is not None
                and self.global_steps == self._config.flops_profiler_config.profile_step
            )
        ):
            return self._fused_train_batch(micro)
        losses = []
        for b in micro:
            loss = self.forward(b)
            self.backward(loss)
            self.step()
            losses.append(loss)
        # one batched fetch, not gas sequential round-trips (each
        # device_get is a blocking host RTT on the tunneled backend);
        # async-copy enqueue first so the transfers overlap each other
        with self.tracer.span("train.loss_fetch") as sp:
            _enqueue_host_copies(losses)
            vals = jax.device_get(losses)
        if self.tracer.enabled:
            self.metrics.histogram("train.loss_fetch_ms").observe(sp.duration_ms)
        return sum(vals) / len(vals)

    def _fused_train_batch(self, micro):
        """Single-dispatch optimizer step (``compile.fuse_grad_accum``): the
        gas microbatches are stacked along a scan axis and one jitted
        program runs fwd+bwd+accumulate per microbatch plus the optimizer
        update. The full state tuple (params, master, opt_state,
        scale_state) is donated, so XLA updates it in place. Returns the
        window's mean loss as a host scalar (same contract as the unfused
        loop)."""
        gas = self.gradient_accumulation_steps()
        self.tput_timer.start()
        self.timers(FORWARD_GLOBAL_TIMER).start()
        t_step0 = self.tracer.clock()
        if self.curriculum_scheduler is not None:
            seqlen = self.curriculum_scheduler.update_difficulty(self.global_steps + 1)
            micro = [_truncate_seq(b, seqlen) for b in micro]
        with self.tracer.span("train.h2d"):
            stacked = self._place_stacked_batch(micro)
        model_kwargs = self._model_kwargs()  # pld theta; random-LTD is gated off
        parent_rng = self._rng
        lr = self.optimizer.param_groups[0]["lr"]
        dispatch_span = self.tracer.span("train.dispatch", program="fused_accum_step")
        if self.mixed_precision:
            with dispatch_span:
                out = self._jit_fused_accum_step(
                    self._params, self._master, self._opt_state, self._scale_state,
                    lr, self._rng, stacked, model_kwargs,
                )
            (
                loss,
                self._params,
                self._master,
                self._opt_state,
                self._scale_state,
                self._last_grad_norm,
                overflow_flag,
                pre_scale,
                self._rng,
            ) = out
        else:
            with dispatch_span:
                out = self._jit_fused_accum_step(
                    self._master, self._opt_state, self._scale_state,
                    lr, self._rng, stacked, model_kwargs,
                )
            (
                loss,
                self._master,
                self._opt_state,
                self._scale_state,
                self._last_grad_norm,
                overflow_flag,
                pre_scale,
                self._rng,
            ) = out
            self._params = self._master
        self._last_loss = loss
        # a fallback window (per-microbatch protocol) may have lazily
        # allocated the accumulator; the fused step neither reads nor zeroes
        # it, so drop it — keeping it would hand get_last_grads a stale
        # all-zero tree AND pin a param-sized buffer the fusion exists to free
        self._grad_acc = None
        # debug-grad stash (get_last_grads recomputes the LAST microbatch's
        # grads): host batch reference, the parent rng the program split,
        # and the pre-update scale it consumed (an output — scale_state was
        # donated)
        self._last_batch = micro[-1]
        self._last_fwd_rng = parent_rng
        self._last_model_kwargs = model_kwargs
        self._last_fwd_scale = pre_scale
        self.timers(FORWARD_GLOBAL_TIMER).stop(sync=False)
        self.timers(STEP_GLOBAL_TIMER).start()
        self.micro_steps += gas
        self.global_samples += (
            self.train_micro_batch_size_per_gpu() * self.data_parallel_world_size() * gas
        )
        self.metrics.counter("train.steps").inc()
        self._finish_step_bookkeeping(overflow_flag)
        self.timers(STEP_GLOBAL_TIMER).stop(sync=False)
        self.tput_timer.stop(global_step=True)
        with self.tracer.span("train.loss_fetch"):
            _enqueue_host_copies((loss,))
            val = jax.device_get(loss)
        if self.tracer.enabled:
            # the whole fused optimizer step, host-side wall clock (the
            # loss fetch closes the window — the one sanctioned blocking
            # read, so this includes the device time the dispatch hid)
            t_now = self.tracer.clock()
            self.tracer.add_span("train.step", t_step0, t_now, gas=gas, fused=True)
            self.metrics.histogram("train.step_ms").observe((t_now - t_step0) * 1e3)
        return val

    def _split_step_batch(self, batch, gas: int):
        """Slice a full-step batch into gas microbatches along the leading dim."""
        if gas == 1:
            return [batch]
        leaves = jax.tree_util.tree_leaves(batch)
        B = np.shape(leaves[0])[0]
        expected = self.train_batch_size()
        if B != expected and not getattr(self, "_warned_step_batch", False):
            self._warned_step_batch = True
            logger.warning(
                f"train_batch(batch=...) got leading dim {B} but the config batch "
                f"triad implies a full-step batch of {expected}; slicing into "
                f"{gas} microbatches of {B // gas}"
            )
        if B % gas != 0:
            raise ValueError(
                f"train_batch(batch=...) leading dim {B} is not divisible by "
                f"gradient_accumulation_steps={gas}"
            )
        b = B // gas
        return [
            jax.tree_util.tree_map(lambda l: l[g * b : (g + 1) * b], batch)
            for g in range(gas)
        ]

    # ------------------------------------------------------------------
    # multi-step training windows (compile.multi_step; ISSUE 14)
    # ------------------------------------------------------------------
    def _window_loader(self, data_iter):
        """The engine's double-buffered input pipeline: wrap the live
        ``data_iter`` in a :class:`PrefetchingLoader` (cached by iterator
        identity) whose ``place_fn`` is the engine's sharded ``device_put``
        — batch i+1's h2d is enqueued while step/window i computes. The
        wrapper snapshots ``training_dataloader``'s cursor before each
        ahead-pull, so checkpoints cut mid-prefetch keep the PR-8
        mid-epoch exact-resume contract (see ``_data_cursor_state``)."""
        from deepspeed_tpu.runtime.dataloader import PrefetchingLoader

        if self._active_prefetcher is not None and self._prefetch_key == id(data_iter):
            return self._active_prefetcher
        if (
            self._active_prefetcher is not None
            and data_iter is not self._active_prefetcher
            and self._active_prefetcher.buffered()
        ):
            # switching iterators strands the old wrapper's staged batches:
            # they were pulled from the PREVIOUS stream and cannot be fed
            # into the new one. Say so — silently skipping samples is the
            # failure mode the cursor machinery exists to prevent.
            logger.warning(
                f"multi_step prefetcher: a new data iterator replaces one "
                f"with {self._active_prefetcher.buffered()} staged-but-"
                "untrained batch(es); those samples are dropped. Drive "
                "epochs through one continuous iterator (e.g. a "
                "RepeatingLoader) or set compile.multi_step.prefetch=false"
            )
        if isinstance(data_iter, PrefetchingLoader):
            self._active_prefetcher = data_iter
            self._prefetch_key = id(data_iter)
            return data_iter
        gas = self.gradient_accumulation_steps()
        depth = (
            gas * self._window_horizon
            if self._config.compile_config.multi_step.prefetch
            else 0
        )
        state_source = (
            self.training_dataloader
            if self.training_dataloader is not None
            and hasattr(self.training_dataloader, "state_dict")
            else None
        )
        self._active_prefetcher = PrefetchingLoader(
            data_iter, place_fn=self._place_batch, depth=depth,
            state_source=state_source,
        )
        self._prefetch_key = id(data_iter)
        return self._active_prefetcher

    def _window_break(self, reason: str):
        self._window_metrics["window_break_reasons"][reason] += 1
        return _NO_WINDOW

    def _window_lrs(self, n: int):
        """The next ``n`` lr values the host schedule would produce, WITHOUT
        advancing it: snapshot -> replay -> restore (load_state_dict
        re-applies the restored lr to the param groups — the PR-8 resume
        contract this replay leans on). The window program indexes this
        array with its in-carry cursor, so overflow-skipped steps re-use
        their lr exactly like the sequential path (which skips the host
        ``lr_scheduler.step()`` on overflow)."""
        import copy as _copy

        lr0 = float(self.optimizer.param_groups[0]["lr"])
        if self.lr_scheduler is None or n == 1:
            return [lr0] * n
        sd = _copy.deepcopy(self.lr_scheduler.state_dict())
        group_lrs = [g["lr"] for g in self.optimizer.param_groups]
        lrs = [lr0]
        try:
            for _ in range(n - 1):
                self.lr_scheduler.step()
                lrs.append(float(self.optimizer.param_groups[0]["lr"]))
        finally:
            self.lr_scheduler.load_state_dict(sd)
            # load_state_dict re-applies the lr only for a scheduler that
            # has stepped (last_batch_iteration >= 0); on a NEVER-stepped
            # one the replay above would otherwise leak its last warmup
            # value into the live param groups — and an all-overflow first
            # window (the normal fp16 scale-settling phase) has no
            # scheduler.step() to self-correct it before the next window
            # reads lr0
            for group, lr in zip(self.optimizer.param_groups, group_lrs):
                group["lr"] = lr
        return lrs

    def _try_train_window(self, data_iter):
        """Form and dispatch ONE fused N-step window, or return the
        ``_NO_WINDOW`` sentinel (counting why in ``window_break_reasons``)
        so the caller falls back to the bit-identical single-step path.
        Windows only form when the whole horizon fits before the next
        host-visible schedule event — checkpoint-interval boundary,
        monitor flush, flops-profiler step — and a full horizon of data
        exists; they therefore never straddle a checkpoint interval (the
        crash contract ``train.mid_window`` chaos kills exercise)."""
        gas = self.gradient_accumulation_steps()
        H = self._window_horizon
        if self.micro_steps % gas != 0:
            return _NO_WINDOW  # mid-accumulation window: sequential owns it
        ccfg = self._config.checkpoint_config
        if ccfg.save_dir and ccfg.interval_steps > 0:
            to_boundary = ccfg.interval_steps - (self.global_steps % ccfg.interval_steps)
            if to_boundary < H:
                return self._window_break("checkpoint")
        if self.monitor is not None:
            interval = (
                self._config.monitor_config.interval_steps
                or self._config.steps_per_print
            )
            to_flush = interval - (self.global_steps % interval)
            if to_flush < H:
                return self._window_break("monitor")
        if self.flops_profiler is not None:
            p = self._config.flops_profiler_config.profile_step
            if self.global_steps <= p < self.global_steps + H:
                return self._window_break("profiler")
        loader = self._window_loader(data_iter)
        if loader.fill(gas * H) < gas * H:
            return self._window_break("data")
        with self.tracer.span("train.window", steps=H, gas=gas):
            with self.tracer.span("train.data_fetch", gas=gas * H):
                micro = [next(loader) for _ in range(gas * H)]
            with self.tracer.span("train.h2d"):
                stacked = self._place_stacked_batch(micro)
            lrs = np.asarray(self._window_lrs(H), np.float32)
            if self._streamed_offload:
                # gather the full host-resident master/moments device-ward
                # (bucketed H2D through the same stream helpers) so the
                # window program scans the IDENTICAL fused step body; the
                # updated state scatters back D2H after the window commits
                ho = self._host_offload
                with self.tracer.span("train.offload_h2d", window=True):
                    g_masters, g_ms, g_vs = ho.gather_device_state()
                if self.mixed_precision:
                    self._master = ho.unflatten(g_masters)
                else:
                    self._master = self._params
                self._opt_state = AdamState(
                    step=jax.device_put(
                        jnp.int32(ho.step_count), self._opt_shardings.step
                    ),
                    exp_avg=ho.unflatten(g_ms),
                    exp_avg_sq=ho.unflatten(g_vs),
                )
            window_name = f"fused_window_step_n{H}"
            with self.tracer.span("train.dispatch", program=window_name):
                if self.mixed_precision:
                    (
                        self._params,
                        self._master,
                        self._opt_state,
                        self._scale_state,
                        self._rng,
                        per_step,
                        last_scale,
                        last_rng_in,
                    ) = self._jit_fused_window_step(
                        self._params, self._master, self._opt_state,
                        self._scale_state, lrs, self._rng, stacked,
                    )
                else:
                    (
                        self._master,
                        self._opt_state,
                        self._scale_state,
                        self._rng,
                        per_step,
                        last_scale,
                        last_rng_in,
                    ) = self._jit_fused_window_step(
                        self._master, self._opt_state, self._scale_state,
                        lrs, self._rng, stacked,
                    )
                    self._params = self._master
        # async loss drain: enqueue the host copies NOW; the blocking read
        # happens one window deferred (bf16/fp32) or at window end (fp16,
        # whose host bookkeeping needs the overflow verdicts)
        for step_out in per_step:
            _enqueue_host_copies(step_out)
        # a fallback window may have lazily allocated the accumulator; the
        # fused window neither reads nor zeroes it (same as the fused-accum
        # path) — drop it rather than hand get_last_grads a stale tree
        self._grad_acc = None
        # debug-grad stash: the LAST step's entering rng and pre-update
        # scale came back as program outputs, so get_last_grads replays the
        # exact key/scale schedule the window consumed
        self._last_batch = micro[-1]
        self._last_fwd_rng = last_rng_in
        self._last_model_kwargs = {}
        self._last_fwd_scale = last_scale
        self._window_metrics["window_steps"] += 1
        self._window_metrics["windowed_opt_steps"] += H
        self.metrics.counter("train.window_steps").inc()
        chaos.point("train.mid_window")
        base_step = self.global_steps
        recs = []
        if self._config.fp16_enabled:
            # fp16's per-step bookkeeping (skip counters, lr-schedule
            # advancement, the next window's lr pre-evaluation) is a
            # function of the overflow verdicts — drain this window now.
            # Still ONE batched fetch per N steps, vs one per step before.
            with self.tracer.span("train.loss_drain", steps=H):
                host_vals = jax.device_get(per_step)
            for k, ((loss, norm, ovf), (h_loss, h_norm, h_ovf)) in enumerate(
                zip(per_step, host_vals)
            ):
                recs.append({"loss": loss, "norm": norm, "ovf": bool(h_ovf)})
                self._append_drained({
                    "step": base_step + k + 1,
                    "loss": float(h_loss),
                    "grad_norm": float(h_norm),
                    "overflow": bool(h_ovf),
                })
        else:
            for loss, norm, _ovf in per_step:
                recs.append({"loss": loss, "norm": norm, "ovf": None})
            self._pending_drains.append({"base_step": base_step, "vals": per_step})
            # one-window-deferred: everything up to window i-1 is surely
            # materialized by now (its compute finished while window i was
            # being formed), so this read does not block the pipeline
            self._drain_pending(keep=1)
        if self._streamed_offload:
            # scatter the window's updated master/moments back to the host
            # buffers; overflow-skipped steps never advanced opt.step inside
            # the window (the where-revert restores it), so the host step
            # counter advances by the taken steps only
            ho = self._host_offload
            taken = (
                H - sum(1 for r in recs if r["ovf"])
                if self._config.fp16_enabled
                else H
            )
            with self.tracer.span("train.offload_d2h", window=True):
                ho.scatter_device_state(
                    jax.tree_util.tree_leaves(self._master),
                    jax.tree_util.tree_leaves(self._opt_state.exp_avg),
                    jax.tree_util.tree_leaves(self._opt_state.exp_avg_sq),
                    taken,
                )
            # the host copies are authoritative again; drop the device set
            self._master = None
            self._opt_state = None
        self._window_stash.extend(recs)
        return self._commit_window_step()

    def _commit_window_step(self):
        """Commit ONE already-computed window step to the host bookkeeping:
        counters, lr schedule, fp16 skip accounting, interval auto-save and
        monitor flush (both of which, by the formation clamp, can only fire
        at the LAST step of a window — when the counters have caught up
        with the device state)."""
        rec = self._window_stash.popleft()
        gas = self.gradient_accumulation_steps()
        self.tput_timer.start()
        self._last_loss = rec["loss"]
        self._last_grad_norm = rec["norm"]
        self.micro_steps += gas
        self.global_samples += (
            self.train_micro_batch_size_per_gpu() * self.data_parallel_world_size() * gas
        )
        self.metrics.counter("train.steps").inc()
        with self.tracer.span("train.step_commit"):
            self._finish_step_bookkeeping(rec["ovf"])
        self.tput_timer.stop(global_step=True)
        return rec["loss"]

    def _drain_pending(self, keep: int = 0) -> None:
        while len(self._pending_drains) > keep:
            pend = self._pending_drains.popleft()
            with self.tracer.span("train.loss_drain", steps=len(pend["vals"])):
                host = jax.device_get(pend["vals"])
            for k, (h_loss, h_norm, h_ovf) in enumerate(host):
                self._append_drained({
                    "step": pend["base_step"] + k + 1,
                    "loss": float(h_loss),
                    "grad_norm": float(h_norm),
                    "overflow": bool(h_ovf),
                })

    def _append_drained(self, entry: Dict[str, Any]) -> None:
        """Append to the bounded drained-loss log, counting evictions so
        ``drained_losses()`` can say when it is NOT the whole curve."""
        if len(self._drained_log) == self._drained_log.maxlen:
            self._drained_dropped += 1
        self._drained_log.append(entry)

    def flush_loss_drain(self) -> None:
        """Force the deferred loss drain: after this, ``drained_losses()``
        covers every committed window step. Call at end of training (or
        before reading the full loss curve)."""
        self._drain_pending(keep=0)

    def drained_losses(self):
        """Host-side per-step results delivered by the (deferred) window
        loss drain: a list of ``{step, loss, grad_norm, overflow}`` dicts
        in step order. Values are bit-identical to what per-step
        ``device_get`` calls would have returned — only their delivery is
        deferred. The log is BOUNDED (4096 entries): read it incrementally
        on long runs — ``window_stats()["drained_dropped"]`` counts
        entries the bound evicted unread, so a truncated curve is never
        mistaken for a complete one. ``load_checkpoint`` resets the log to
        the resumed timeline (the replayed steps re-drain); flush and read
        before loading if the pre-load curve matters."""
        return list(self._drained_log)

    def window_stats(self) -> Dict[str, Any]:
        """Multi-step training window telemetry, mirroring the serving
        side's ``serve_stats()`` window block: window counts, why windows
        broke, and ``dispatches_per_opt_step`` — total train-program
        dispatches (from compile telemetry) over optimizer steps, the
        number the windows exist to drive to 1/N."""
        stats = self._telemetry.stats()
        step_programs = {"fwd_bwd", "step", "fused_step", "fused_accum_step",
                         "grad_stats", "offload_stats", "zero_grads"}
        dispatches = sum(
            rec["dispatches"]
            for name, rec in stats.items()
            if name in step_programs
            or name.startswith("fused_window_step")
            or name.startswith("offload_bucket_update")
        )
        return {
            "multi_step_enabled": self._window_armed,
            "window_horizon": self._window_horizon,
            "window_steps": self._window_metrics["window_steps"],
            "windowed_opt_steps": self._window_metrics["windowed_opt_steps"],
            "opt_steps": self.global_steps,
            "window_break_reasons": dict(self._window_metrics["window_break_reasons"]),
            "dispatches": dispatches,
            "dispatches_per_opt_step": (
                dispatches / self.global_steps if self.global_steps else 0.0
            ),
            "pending_loss_drains": len(self._pending_drains),
            "stashed_steps": len(self._window_stash),
            "drained_dropped": self._drained_dropped,
        }

    def offload_stream_stats(self) -> Optional[Dict[str, Any]]:
        """Cumulative H2D/D2H stream accounting for the streamed host
        offload path (``HostOffloadStreamer.stream_stats()``): wall time
        spent issuing async copies, wall time EXPOSED (blocking waits the
        pipeline knobs could not hide), bytes each way, and optimizer
        steps taken. ``None`` when the streamed path is not active —
        including before the first ``train_batch`` (initialization is
        lazy)."""
        if not self._streamed_offload or self._host_offload is None:
            return None
        return self._host_offload.stream_stats()

    def _data_cursor_state(self):
        """The data cursor a checkpoint should carry. When the prefetching
        wrapper has pulled ahead of training, the TRUE cursor is the one
        before the first undelivered batch (the wrapper's snapshot), not
        the loader's over-advanced one — otherwise a resumed run would skip
        the staged-but-untrained batches."""
        pl = self._active_prefetcher
        if (
            pl is not None
            and self.training_dataloader is not None
            and getattr(pl, "_state_source", None) is self.training_dataloader
        ):
            return pl.state_dict()
        if self.training_dataloader is not None and hasattr(
            self.training_dataloader, "state_dict"
        ):
            return self.training_dataloader.state_dict()
        return None

    # ------------------------------------------------------------------
    # checkpointing (reference: engine.py:2961 save / :2638 load)
    # ------------------------------------------------------------------
    def _ckpt_dir(self, save_dir: str, tag: str) -> str:
        return os.path.join(save_dir, str(tag))

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None, client_state: Optional[Dict] = None, save_latest: bool = True, exclude_frozen_parameters: bool = False, asynchronous: Optional[bool] = None):  # noqa: ARG002
        """Write one atomic checkpoint under ``save_dir/tag``.

        The payload carries the FULL replay state — module/master/optimizer
        trees, loss-scale state, LR-schedule state, step counters, the PRNG
        key, and the data-sampler cursor — so a
        ``load_checkpoint(auto_resume=True)`` run produces losses
        bit-identical to the uninterrupted one. Persistence is atomic
        (stage → fsync → rename, then the ``latest`` marker): a ``kill -9``
        at any instant leaves the newest *valid* checkpoint discoverable.
        ``asynchronous`` (default: ``checkpoint.async_snapshot``) snapshots
        device→host and persists from a background writer so the step loop
        only pays the D2H copy (``checkpoint_stats()['last_stall_ms']``)."""
        if not self._initialized:
            raise RuntimeError("cannot save before the engine state is initialized")
        if self._pending_commit is not None:
            raise RuntimeError(
                "save_checkpoint() called with a pending fused step: forward() "
                "already applied the optimizer update but step() has not adopted "
                "it (counters/lr would be inconsistent); call step() first"
            )
        if self._window_stash:
            raise RuntimeError(
                "save_checkpoint() called mid-window: the fused multi-step "
                "program already advanced the model state but "
                f"{len(self._window_stash)} step(s) are uncommitted "
                "(counters/lr would be inconsistent); finish the window's "
                "train_batch calls first"
            )
        if tag is None:
            tag = f"global_step{self.global_steps}"
        tag = self._validate_checkpoint_tag(tag)
        path = self._ckpt_dir(save_dir, tag)
        self.checkpoint_engine.create(tag)
        if self._param_stream is not None:
            # fp32 master + moments are the streamer's host state; module
            # weights are the host-backed compute-dtype store
            master = None
            optimizer_state = {"param_stream": self._param_stream.state_dict()}
            module_state = self._param_stream.gathered_params()
        elif self._host_offload is not None:
            # the fp32 master lives inside the host-offload state dict; a
            # second device-side copy would double checkpoint size AND
            # materialize fp32 master in HBM (the memory offload avoids)
            master = None
            optimizer_state = {"host_offload": self._host_offload.state_dict()}
            module_state = self._params
        else:
            master = self._master if self.mixed_precision else None
            optimizer_state = _namedtuple_to_dict(self._opt_state)
            module_state = self._params
        state = {
            "module": module_state,
            "master": master,
            "optimizer": optimizer_state,
            "loss_scaler": _namedtuple_to_dict(self._scale_state),
            "lr_scheduler": self.lr_scheduler.state_dict() if self.lr_scheduler is not None else None,
            "random_ltd": self.random_ltd_scheduler.state_dict()
            if self.random_ltd_scheduler is not None
            else None,
            "moq": self.quantizer.state_dict() if self.quantizer is not None else None,
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            # exact-resume replay state: the PRNG key the next step would
            # split, the data-sampler cursor, and the mesh topology (a
            # load into a different mesh fails loudly, not via reshape)
            "rng": np.asarray(jax.device_get(self._rng)),
            # via _data_cursor_state: when the prefetching wrapper has
            # staged batches ahead, the cursor of the first UNDELIVERED
            # batch is saved, not the loader's over-advanced one
            "data_cursor": self._data_cursor_state(),
            "mesh": dict(zip(self.mesh.axis_names, map(int, self.mesh.devices.shape))),
            "ds_config": self._config._param_dict,
            "ds_version": _version(),
            "client_state": client_state or {},
        }
        update_latest = save_latest and dist.get_rank() == 0
        use_async = (
            self._config.checkpoint_config.async_snapshot
            if asynchronous is None
            else bool(asynchronous)
        )
        if use_async and (
            dist.get_world_size() > 1 or not tree_fully_addressable(state)
        ):
            # multi-process saves are collective (every rank participates
            # in one orbax write to one shared dir; rank 0 commits) and a
            # cross-process global array has no single-host copy — both
            # must go through the synchronous path
            logger.warning(
                "async_snapshot: multi-process / non-addressable state — "
                "falling back to a synchronous collective save"
            )
            use_async = False
        if not use_async:
            # a synchronous save (including the fallback above) must not
            # interleave with queued async writes: an in-flight older
            # snapshot finishing AFTER this save would regress the latest
            # marker (and a same-tag re-save would reclaim the writer's
            # live staging dir)
            self.wait_pending_checkpoint()
        t0 = time.perf_counter()
        if use_async:
            if self._ckpt_writer is None:
                self._ckpt_writer = AsyncCheckpointWriter(
                    self.checkpoint_engine,
                    max_inflight=self._config.checkpoint_config.max_inflight_snapshots,
                    tracer=self.tracer,
                )
            # the ONLY on-step cost: device->host of the state tuple. It
            # must complete before returning — the step programs donate
            # these buffers, so the next dispatch invalidates them.
            with self.tracer.span("ckpt.d2h_stall", tag=tag):
                host_state = host_snapshot(state)
            stall_ms = (time.perf_counter() - t0) * 1e3
            if self.tracer.enabled:
                self.metrics.histogram("ckpt.stall_ms").observe(stall_ms)
            self._ckpt_writer.submit(
                host_state, path, tag, save_dir if update_latest else None
            )
            self._ckpt_metrics["async_saves"] += 1
            self._ckpt_metrics["last_stall_ms"] = stall_ms
            self._ckpt_metrics["total_stall_ms"] += stall_ms
        else:
            self.checkpoint_engine.save(state, path)
            # the save was collective (all ranks, one shared staging dir);
            # the commit rename is rank 0's alone — and it happens BEFORE
            # the latest marker, which may only ever name a fully
            # committed checkpoint
            if dist.get_rank() == 0:
                self.checkpoint_engine.commit(tag)
                if update_latest:
                    write_latest_marker(save_dir, tag)
            else:
                self.checkpoint_engine.discard_staged(tag)
            self._ckpt_metrics["last_save_s"] = time.perf_counter() - t0
        self._ckpt_metrics["saves"] += 1
        dist.barrier(name="save_checkpoint")
        return True

    def wait_pending_checkpoint(self) -> None:
        """Fence the async checkpoint writer: returns once every queued
        snapshot is committed; re-raises a background persist failure."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait()
            if self._ckpt_writer.saves:
                self._ckpt_metrics["last_save_s"] = self._ckpt_writer.last_save_s

    def checkpoint_stats(self) -> Dict[str, Any]:
        """Checkpoint telemetry next to ``compile_stats()``: save counts,
        the async snapshot stall (``last_stall_ms`` — the step-time hit
        while a write is in flight; the bench records it as
        ``ckpt_stall_ms``), full persist and restore wall times, and the
        writer's queue depth."""
        out = dict(self._ckpt_metrics)
        out["async_snapshot"] = self._config.checkpoint_config.async_snapshot
        out["pending"] = self._ckpt_writer.pending() if self._ckpt_writer else 0
        return out

    def _validate_checkpoint_tag(self, tag: str) -> str:
        """Cross-rank tag equality check (reference engine.py:2944).

        Returns the tag to USE. On mismatch: Fail raises; Warn warns and
        adopts rank 0's tag — checkpoints here are collective global-array
        saves, so ranks entering different tags would deadlock the save
        (the reference writes per-rank files and merely produces a
        scattered checkpoint; a coherent save under one tag is the
        TPU-native equivalent of 'proceed with a warning')."""
        if not self._config.checkpoint_tag_validation_enabled or dist.get_world_size() == 1:
            return tag
        tags = dist.all_gather_object(tag)
        if any(t != tag for t in tags):
            msg = f"checkpoint tag mismatch across ranks: {tags}"
            if self._config.checkpoint_tag_validation_fail:
                raise RuntimeError(msg)
            logger.warning(msg + f" — saving under rank 0's tag {tags[0]!r}")
            return tags[0]
        return tag

    def load_checkpoint(
        self,
        load_dir: str,
        tag: Optional[str] = None,
        load_module_strict: bool = True,
        load_optimizer_states: bool = True,
        load_lr_scheduler_states: bool = True,
        load_module_only: bool = False,
        custom_load_fn: Optional[Callable] = None,  # noqa: ARG002
        auto_resume: bool = False,
    ):
        """Load a checkpoint. With ``auto_resume=True`` the newest VALID
        checkpoint under ``load_dir`` is discovered by scanning and
        validating every tag (the ``latest`` marker is only a hint — a kill
        between commit and the marker update leaves a newer valid
        checkpoint unnamed), the full replay state (PRNG key, data cursor,
        loss scale, counters, LR schedule) is restored, and the resumed
        run's losses are bit-identical to an uninterrupted one. With
        ``load_module_strict`` (default) every module leaf is validated
        against the live state first — a shape/dtype/mesh mismatch raises
        one clear ``CheckpointLoadError`` naming the offending leaf."""
        if self._window_stash:
            raise RuntimeError(
                "load_checkpoint() called mid-window: "
                f"{len(self._window_stash)} computed step(s) are uncommitted; "
                "finish the window's train_batch calls (or rebuild the "
                "engine) before loading"
            )
        self.wait_pending_checkpoint()
        t_load = time.perf_counter()
        state = None
        if tag is None:
            if auto_resume:
                # newest valid first, falling back past any tag that turns
                # out torn at load time (a structurally complete-looking
                # directory can still fail its pickle/array restore —
                # CheckpointCorruptError means 'skip this tag', not 'die')
                for cand in reversed(list_valid_tags(load_dir)):
                    try:
                        state = self.checkpoint_engine.load(
                            self._ckpt_dir(load_dir, cand)
                        )
                        tag = cand
                        break
                    except CheckpointCorruptError as e:
                        logger.warning(
                            f"auto_resume: skipping torn checkpoint {cand}: {e}"
                        )
                if state is None:
                    logger.warning(
                        f"auto_resume: no valid checkpoint under {load_dir}; "
                        "nothing loaded (fresh start)"
                    )
                    return None, {}
            else:
                latest = os.path.join(load_dir, "latest")
                if not os.path.isfile(latest):
                    logger.warning(f"no 'latest' file at {latest}; nothing loaded")
                    return None, {}
                with open(latest) as f:
                    tag = f.read().strip()
        path = self._ckpt_dir(load_dir, tag)
        if state is None:
            state = self.checkpoint_engine.load(path)
        if not self._initialized:
            raise RuntimeError(
                "engine state must be initialized before load_checkpoint (call init_params "
                "with a sample batch, or run one forward)"
            )
        if load_module_strict:
            self._validate_checkpoint_state(state, path)
        if self._param_stream is not None:
            opt_state = state.get("optimizer")
            if not (isinstance(opt_state, dict) and "param_stream" in opt_state):
                raise NotImplementedError(
                    "param-offload load_checkpoint requires a checkpoint saved "
                    "by the param-offload engine (optimizer['param_stream'])"
                )
            if load_optimizer_states and not load_module_only:
                self._param_stream.load_state_dict(opt_state["param_stream"])
            else:
                # weights only: fresh moments + step count
                self._param_stream.load_master_state(opt_state["param_stream"])
            if state.get("loss_scaler") is not None:
                self._scale_state = jax.device_put(
                    _dict_to_namedtuple(_host_scalar_tree(state["loss_scaler"]), LossScaleState)
                )
            if load_lr_scheduler_states and self.lr_scheduler is not None and state.get("lr_scheduler"):
                self.lr_scheduler.load_state_dict(state["lr_scheduler"])
            if not load_module_only:
                self.global_steps = state.get("global_steps", 0)
                self.global_samples = state.get("global_samples", 0)
                self.micro_steps = state.get("micro_steps", 0)
                self.skipped_steps = state.get("skipped_steps", 0)
                self._restore_replay_state(state)
                if self.progressive_layer_drop is not None:
                    self.progressive_layer_drop.update_state(self.global_steps)
            self._ckpt_metrics["last_restore_s"] = time.perf_counter() - t_load
            return path, state.get("client_state", {})
        # non-offload fp32: module state IS the master — place it with the
        # master sharding the (donating) step programs pin, mirroring
        # init_params; everywhere else params keep their param sharding
        fp32_single_copy = not self.mixed_precision and self._host_offload is None
        put_p = jax.jit(
            lambda t: t,
            out_shardings=self._master_shardings if fp32_single_copy else self._param_shardings,
        )
        self._params = put_p(_as_device_tree(state["module"]))
        if self._host_offload is not None:
            opt_state = state.get("optimizer")
            if isinstance(opt_state, dict) and "host_offload" in opt_state:
                if not (load_optimizer_states and not load_module_only):
                    # module-only load must still refresh the host master, or
                    # the next step clobbers the loaded weights with the
                    # stale init-time master
                    self._host_offload.load_master_only(opt_state["host_offload"])
            elif state.get("master") is not None:
                # checkpoint from a non-offload run: adopt its master —
                # HOST leaves (set_master_leaves copies host-side; a device
                # round-trip would spike HBM exactly where offload avoids it)
                self._host_offload.set_master_leaves(_host_leaves(state["master"]))
            else:
                # fp32 non-offload checkpoint: module weights ARE the master
                self._host_offload.set_master_leaves(_host_leaves(state["module"]))
        elif self.mixed_precision and state.get("master") is not None:
            put_m = jax.jit(lambda t: t, out_shardings=self._master_shardings)
            self._master = put_m(_as_device_tree(state["master"]))
        elif self.mixed_precision:
            # checkpoint carries no fp32 master (saved by an offload engine or
            # module-only): rebuild it from the loaded module weights, or the
            # next step would cast the stale init-time master over them
            put_m = jax.jit(
                lambda t: jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), t),
                out_shardings=self._master_shardings,
            )
            self._master = put_m(self._params)
        else:
            self._master = self._params
        if load_optimizer_states and not load_module_only and state.get("optimizer") is not None:
            if self._host_offload is not None:
                self._host_offload.load_state_dict(state["optimizer"]["host_offload"])
            elif isinstance(state["optimizer"], dict) and (
                "param_stream" in state["optimizer"] or "host_offload" in state["optimizer"]
            ):
                kind = "param_stream" if "param_stream" in state["optimizer"] else "host_offload"
                raise NotImplementedError(
                    f"this checkpoint's optimizer state was saved by the {kind} "
                    "offload engine and cannot be loaded into a non-offload "
                    "engine; pass load_optimizer_states=False to adopt the "
                    "module weights with a fresh optimizer"
                )
            else:
                opt = _dict_to_namedtuple(state["optimizer"], type(self._opt_state))
                put_o = jax.jit(lambda t: t, out_shardings=self._opt_shardings)
                self._opt_state = put_o(_as_device_tree(opt))
        if state.get("loss_scaler") is not None:
            self._scale_state = jax.device_put(
                _dict_to_namedtuple(_host_scalar_tree(state["loss_scaler"]), LossScaleState)
            )
        if load_lr_scheduler_states and self.lr_scheduler is not None and state.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(state["lr_scheduler"])
        if self.random_ltd_scheduler is not None and state.get("random_ltd"):
            self.random_ltd_scheduler.load_state_dict(state["random_ltd"])
        if self.quantizer is not None and state.get("moq"):
            self.quantizer.load_state_dict(state["moq"])
        if not load_module_only:
            self.global_steps = state.get("global_steps", 0)
            self.global_samples = state.get("global_samples", 0)
            self.micro_steps = state.get("micro_steps", 0)
            self.skipped_steps = state.get("skipped_steps", 0)
            self._restore_replay_state(state)
            if self.progressive_layer_drop is not None:
                # theta is a pure function of global_steps — recompute it so
                # the first resumed step drops layers like an uninterrupted run
                self.progressive_layer_drop.update_state(self.global_steps)
        client_state = state.get("client_state", {})
        self._ckpt_metrics["last_restore_s"] = time.perf_counter() - t_load
        return path, client_state

    def _restore_replay_state(self, state: Dict) -> None:
        """The exact-resume tail: the PRNG key the next step will split and
        the data-sampler cursor. Checkpoints from before these fields
        existed load as before (a warning, not an error — their resume is
        correct-but-not-bit-identical)."""
        rng = state.get("rng")
        if rng is not None:
            self._rng = jnp.asarray(np.asarray(rng))
        else:
            logger.warning(
                "checkpoint carries no RNG state (pre-fault-tolerance save): "
                "resumed dropout/LTD streams will diverge from the "
                "uninterrupted run"
            )
        cursor = state.get("data_cursor")
        if (
            cursor
            and self.training_dataloader is not None
            and hasattr(self.training_dataloader, "load_state_dict")
        ):
            self.training_dataloader.load_state_dict(cursor)
        # a live prefetching wrapper holds batches pulled under the OLD
        # cursor; drop it (the next train_batch re-wraps the caller's
        # post-resume iterator). The pending drains and the drained-loss
        # log belong to the ABANDONED timeline — the resumed run replays
        # (and re-drains) every step past the checkpoint, so keeping them
        # would duplicate or contradict step numbers. Callers wanting the
        # pre-load curve call flush_loss_drain() + drained_losses() BEFORE
        # loading.
        self._active_prefetcher = None
        self._prefetch_key = None
        self._pending_drains.clear()
        self._drained_log.clear()
        self._drained_dropped = 0

    def _validate_checkpoint_state(self, state: Dict, path: str) -> None:
        """Fail fast, with names: a checkpoint whose mesh topology or module
        leaves disagree with the live run must raise ONE clear error — not
        a tree-unflatten or reshape failure three layers down."""
        saved_mesh = state.get("mesh")
        if saved_mesh is not None:
            cur_mesh = dict(zip(self.mesh.axis_names, map(int, self.mesh.devices.shape)))
            if dict(saved_mesh) != cur_mesh:
                raise CheckpointLoadError(
                    f"mesh topology mismatch loading {path}: checkpoint was "
                    f"saved on mesh {dict(saved_mesh)} but this run uses "
                    f"{cur_mesh}; re-shard the checkpoint or rebuild the "
                    "engine with the saved topology"
                )
        module = state.get("module")
        if module is None or self._params is None:
            return  # offload layouts validate their own stores
        from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths

        saved = _flatten_with_paths(module)
        cur = _flatten_with_paths(self._params)
        missing = sorted(set(cur) - set(saved))
        extra = sorted(set(saved) - set(cur))
        if missing or extra:
            raise CheckpointLoadError(
                f"module tree mismatch loading {path}: "
                + (f"checkpoint lacks {missing[:3]}" if missing else "")
                + (" and " if missing and extra else "")
                + (f"checkpoint has unknown {extra[:3]}" if extra else "")
                + " (pass load_module_strict=False to adopt loosely)"
            )
        for name in cur:
            s_leaf, c_leaf = saved[name], cur[name]
            s_shape = tuple(np.shape(s_leaf))
            c_shape = tuple(np.shape(c_leaf))
            if s_shape != c_shape:
                raise CheckpointLoadError(
                    f"shape mismatch loading {path} at module leaf "
                    f"{name!r}: checkpoint has {s_shape}, current state has "
                    f"{c_shape} (model config differs from the one that "
                    "saved this checkpoint)"
                )
            s_dtype = np.dtype(getattr(s_leaf, "dtype", np.asarray(s_leaf).dtype))
            c_dtype = np.dtype(c_leaf.dtype)
            if s_dtype != c_dtype:
                raise CheckpointLoadError(
                    f"dtype mismatch loading {path} at module leaf "
                    f"{name!r}: checkpoint has {s_dtype}, current state has "
                    f"{c_dtype} (precision config differs from the run that "
                    "saved this checkpoint; pass load_module_strict=False "
                    "to skip validation)"
                )

    def consolidated_16bit_state_dict(self) -> Dict[str, Any]:
        """Full compute-dtype weights as a flat host dict (reference
        ``_zero3_consolidated_16bit_state_dict``, engine.py:3373 — the
        all-gather the reference choreographs rank-by-rank is a device_get
        of global arrays here)."""
        from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths

        params = self.get_params()
        return {
            name: np.asarray(jax.device_get(leaf))
            for name, leaf in _flatten_with_paths(params).items()
        }

    def save_reference_checkpoint(self, save_dir: str, tag: Optional[str] = None, dp_shards: Optional[int] = None) -> str:
        """Write the reference's sharded training-checkpoint layout
        (mp_rank_00_model_states.pt + zero_pp_rank_*_optim_states.pt +
        latest) so the reference's own ``zero_to_fp32.py`` can consolidate
        this run (reference ``_save_checkpoint``/``_save_zero_checkpoint``,
        engine.py:2588,2961). See ``checkpoint/reference_export.py``."""
        from deepspeed_tpu.checkpoint.reference_export import export_reference_checkpoint

        # all ranks consolidate (the exporter rank-gates the file writes and
        # barriers before returning), and all return the same path
        return export_reference_checkpoint(self, save_dir, tag=tag, dp_shards=dp_shards)

    def save_16bit_model(self, save_dir: str, save_filename: str = "pytorch_model.bin", exclude_frozen_parameters: bool = False):  # noqa: ARG002
        """Write ONE consolidated compute-dtype weights file loadable without
        the engine (reference ``save_16bit_model``, engine.py:3442).
        ``.bin`` filenames save a torch state dict (torch interop); anything
        else saves an ``npz`` with the same flat names."""
        if not self._initialized:
            raise RuntimeError("cannot save before the engine state is initialized")
        sd = self.consolidated_16bit_state_dict()
        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, save_filename)
        if dist.get_rank() == 0:
            if save_filename.endswith((".bin", ".pt")):
                import torch

                torch.save(
                    {k: torch.from_numpy(np.ascontiguousarray(v.astype(np.float32))) for k, v in sd.items()},
                    path,
                )
            else:
                np.savez(path, **sd)
        dist.barrier(name="save_16bit_model")
        return True

    # ------------------------------------------------------------------
    # introspection / utils
    # ------------------------------------------------------------------
    def get_params(self):
        if self._param_stream is not None:
            return self._param_stream.gathered_params()
        return self._params

    def get_param_treedef(self):
        """Tree structure of ``get_params()`` without materializing it — on
        the offload path ``gathered_params`` copies the whole model to host,
        which structure checks (zero.GatheredParameters) must not pay for."""
        if self._param_stream is not None:
            return self._param_stream.params_treedef()
        return jax.tree_util.tree_structure(self._params)

    def get_last_grads(self):
        """Gradient tree of the latest training micro-batch (debug/inspection
        surface behind ``safe_get_full_grad``). On the accumulating path this
        is the live fp32 accumulator; on the fused path grads only exist
        inside the step program, so they are recomputed here on the stashed
        batch with the exact rng and loss scale the step consumed — but at
        the CURRENT (post-update) params, so values differ from the step's
        grads by one optimizer update (and after an fp16 overflow reflect the
        reverted params)."""
        if self._param_stream is not None:
            return self._param_stream.debug_grads()
        if not self._fused_step_enabled and self._grad_acc is not None:
            # contract: fp32 grads whatever grad_accum_dtype stores
            return jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), self._grad_acc
            )
        if self._last_batch is None:
            return None
        if self._jit_debug_grad is None:
            loss_of = self._loss_of  # the step's own loss contract

            def dbg(params, rng, scale, batch, model_kwargs):
                def scaled_loss(p):
                    return loss_of(p, batch, rng, model_kwargs) * scale.astype(jnp.float32)

                g = jax.grad(scaled_loss)(params)
                return jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), g)

            self._jit_debug_grad = self._telemetry.instrument("debug_grad", dbg)
        _, sub = jax.random.split(self._last_fwd_rng)
        if self._fused_accum_enabled and not self._fused_step_enabled:
            # replay the fused-scan key schedule: rng, sub = split(parent);
            # micro_rngs = split(sub, gas) — the last microbatch consumed
            # micro_rngs[-1]
            sub = jax.random.split(sub, self.gradient_accumulation_steps())[-1]
        placed = self._place_batch(self._last_batch)
        kwargs = getattr(self, "_last_model_kwargs", None)
        if kwargs is None:
            kwargs = self._model_kwargs(placed)
        return self._jit_debug_grad(
            self._params, sub, self._last_fwd_scale, placed, kwargs
        )

    def set_params(self, tree) -> None:
        """Adopt a full param tree (host numpy or device arrays) as the new
        model weights: refreshes the fp32 master AND the compute-dtype store
        so the surgery survives the next optimizer step. The write-back half
        of ``zero.GatheredParameters`` (reference re-partitioning on exit,
        partition_parameters.py:1938). Optimizer moments are kept."""
        if not self._initialized:
            raise RuntimeError("set_params before engine state is initialized")
        if self._param_stream is not None:
            stream = self._param_stream
            layers = tree["layers"]
            for i in range(stream.n_layers):
                per_layer = jax.tree_util.tree_map(lambda a: np.asarray(a)[i], layers)
                flat = np.concatenate(
                    [
                        np.asarray(l, np.float32).ravel()
                        for l in jax.tree_util.tree_leaves(per_layer)
                    ]
                )
                stream._layer_state[i].master[:] = flat
            resident = {k: v for k, v in tree.items() if k != "layers"}
            if stream._resident_state.master.size:
                stream._resident_state.master[:] = np.concatenate(
                    [
                        np.asarray(l, np.float32).ravel()
                        for l in jax.tree_util.tree_leaves(resident)
                    ]
                )
            stream._materialize_from_master()
            return
        master32 = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, dtype=jnp.float32), tree
        )
        if self._host_offload is not None:
            self._host_offload.set_master_leaves(jax.tree_util.tree_leaves(master32))
            new_params = self._host_offload.unflatten(
                [
                    jnp.asarray(np.asarray(m), dtype=p.dtype)
                    for m, p in zip(
                        jax.tree_util.tree_leaves(master32),
                        jax.tree_util.tree_leaves(self._params),
                    )
                ]
            )
            self._params = self._jit_reshard_params(new_params)
            return
        put_m = jax.jit(lambda t: t, out_shardings=self._master_shardings)
        self._master = put_m(master32)
        if self.mixed_precision:
            keep32 = getattr(self, "_keep_fp32", None)
            if keep32 is None:
                cast = lambda t: jax.tree_util.tree_map(
                    lambda x: x.astype(self.compute_dtype), t
                )
            else:
                cast = lambda t: jax.tree_util.tree_map(
                    lambda x, keep: x if keep else x.astype(self.compute_dtype), t, keep32
                )
            self._params = jax.jit(cast, out_shardings=self._param_shardings)(self._master)
        else:
            self._params = self._master

    def get_master_params(self):
        if self._param_stream is not None:
            return self._param_stream.master_params()
        if self._host_offload is not None:
            return self._host_offload.unflatten(self._host_offload.master_leaves())
        return self._master

    def num_parameters(self) -> int:
        if not self._initialized:
            return 0
        if self._param_stream is not None:
            return self._param_stream.num_parameters()
        tree = self._params if self._master is None else self._master
        return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def _truncate_seq(batch, seqlen: int):
    """Truncate every rank-≥2 leaf's dim 1 to ``seqlen`` (curriculum)."""

    def leaf(x):
        if np.ndim(x) >= 2 and np.shape(x)[1] > seqlen:
            return x[:, :seqlen]
        return x

    return jax.tree_util.tree_map(leaf, batch)


def _namedtuple_to_dict(nt):
    if nt is None:
        return None
    if hasattr(nt, "_asdict"):
        return {k: _namedtuple_to_dict(v) for k, v in nt._asdict().items()}
    return nt


def _dict_to_namedtuple(d, cls):
    if d is None:
        return None
    fields = cls._fields
    vals = []
    for f in fields:
        v = d[f]
        vals.append(v)
    return cls(*vals)


def _host_leaves(tree):
    """Flat HOST numpy leaves for the host-offload master adoption: numpy
    stays put, addressable device arrays fetch, replicated multi-process
    globals read their local shard; a cross-process-SHARDED master cannot
    be adopted host-side (no local full copy exists) and says so."""
    def leaf(x):
        if isinstance(x, jax.Array):
            if x.is_fully_addressable:
                return np.asarray(jax.device_get(x))
            shard = x.addressable_shards[0]
            if shard.data.shape == x.shape:  # replicated
                return np.asarray(shard.data)
            raise NotImplementedError(
                "adopting a cross-process-sharded master into the "
                "host-offload engine is unsupported (no process holds the "
                "full tensor); save from the offload engine instead"
            )
        return np.asarray(x)

    return [leaf(l) for l in jax.tree_util.tree_leaves(tree)]


def _host_scalar_tree(tree):
    """Loss-scale state leaves are replicated scalars; a multi-process orbax
    restore hands them back as global arrays that a local device_put
    rejects — read the locally-addressable shard instead."""
    def leaf(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return np.asarray(x.addressable_shards[0].data)
        return np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x

    return jax.tree_util.tree_map(leaf, tree)


def _as_device_tree(tree):
    """numpy leaves -> device arrays; jax arrays (possibly multi-process
    GLOBAL arrays from an orbax restore) pass through untouched — a local
    jnp.asarray on a non-addressable global array is an error."""
    return jax.tree_util.tree_map(
        lambda x: x if isinstance(x, jax.Array) else jnp.asarray(x), tree
    )


def _live_topology():
    from deepspeed_tpu.parallel import mesh as mesh_mod

    return mesh_mod._TOPOLOGY


def _config_requests_mesh(config: DeepSpeedConfig) -> bool:
    """True when the config names a mesh shape explicitly (data > 0, or any
    other axis above its size-1 default); all-default means 'derive' and
    defers to a live topology."""
    md = config.mesh_config.model_dump()
    return md.get("data", 0) > 0 or any(v > 1 for k, v in md.items() if k != "data")


def _topology_matches(config: DeepSpeedConfig) -> bool:
    from deepspeed_tpu.parallel import mesh as mesh_mod

    topo = mesh_mod._TOPOLOGY
    if topo is None:
        return False
    try:
        resolved = config.mesh_config.resolve(topo.world_size)
    except Exception:
        return False
    return resolved.model_dump() == topo.config.model_dump()


def _version() -> str:
    from deepspeed_tpu import __version__

    return __version__
