"""Tensor swapping to disk (ZeRO-Infinity).

Counterpart of the reference's ``deepspeed/runtime/swap_tensor/`` built on
the native aio library (``csrc/aio/deepspeed_aio.cpp``).
"""

from deepspeed_tpu.runtime.swap_tensor.aio_config import AioConfig, get_aio_config
from deepspeed_tpu.runtime.swap_tensor.utils import SwapBuffer, SwapBufferManager, SwapBufferPool
from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper
from deepspeed_tpu.runtime.swap_tensor.optimizer_utils import OptimizerSwapper
from deepspeed_tpu.runtime.swap_tensor.partitioned_optimizer_swapper import (
    PartitionedOptimizerSwapper,
)
