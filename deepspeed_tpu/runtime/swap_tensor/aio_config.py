"""AIO config block (reference: ``deepspeed/runtime/swap_tensor/aio_config.py``).

Defaults match the reference (block_size 1MB, queue_depth 8, thread_count 1,
single_submit False, overlap_events True) — validated against its NVMe sweep
harness (csrc/aio/py_test/aio_bench_perf_sweep.py).
"""

from __future__ import annotations

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

AIO_DEFAULT_DICT = {
    "block_size": 1048576,
    "queue_depth": 8,
    "thread_count": 1,
    "single_submit": False,
    "overlap_events": True,
}


class AioConfig(DeepSpeedConfigModel):
    block_size: int = Field(AIO_DEFAULT_DICT["block_size"], ge=4096)
    queue_depth: int = Field(AIO_DEFAULT_DICT["queue_depth"], ge=1)
    thread_count: int = Field(AIO_DEFAULT_DICT["thread_count"], ge=1)
    single_submit: bool = AIO_DEFAULT_DICT["single_submit"]
    overlap_events: bool = AIO_DEFAULT_DICT["overlap_events"]


def get_aio_config(param_dict: dict) -> AioConfig:
    aio_dict = param_dict.get("aio", {}) if isinstance(param_dict, dict) else {}
    return AioConfig(**aio_dict)
