"""Swap buffer management.

Counterpart of the reference's ``swap_tensor/utils.py`` (SwapBuffer :37,
SwapBufferPool :96, SwapBufferManager :120): host-DRAM staging buffers that
tensors are packed into before disk writes and unpacked from after reads.
The reference uses pinned CUDA host tensors; here buffers are aligned numpy
float32 arrays (the TPU runtime stages host transfers itself).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

MIN_AIO_BYTES = 1024**2
AIO_ALIGNED_BYTES = 1024


def swap_in_tensors(swap_handle, buffers: List[np.ndarray], swap_paths: List[str]) -> None:
    """Submit async reads of each path into each buffer (reference utils.py:18)."""
    for buffer, path in zip(buffers, swap_paths):
        swap_handle.async_pread(buffer, path)


def swap_out_tensors(swap_handle, buffers: List[np.ndarray], swap_paths: List[str]) -> None:
    for buffer, path in zip(buffers, swap_paths):
        swap_handle.async_pwrite(buffer, path)


class SwapBuffer:
    """One staging buffer holding multiple packed tensors (reference :37)."""

    def __init__(self, buffer: np.ndarray):
        self.buffer = buffer
        self.reset()

    def reset(self) -> None:
        self.offset = 0
        self.swap_tensors: Dict[int, np.ndarray] = {}
        self.compute_tensors: Dict[int, np.ndarray] = {}
        self.swap_paths: Dict[int, str] = {}
        self.num_elem = 0

    def insert_tensor(self, tensor: np.ndarray, swap_path: str, aligned_numel: int):
        swap_tensor, compute_tensor = self.allocate_tensor(swap_path, tensor.size, aligned_numel)
        compute_tensor[:] = tensor.ravel()
        return swap_tensor, compute_tensor

    def allocate_tensor(self, swap_path: str, numel: int, aligned_numel: int):
        assert self.has_space(aligned_numel)
        assert aligned_numel >= numel
        allocate_offset = self.offset
        swap_tensor = self.buffer[allocate_offset : allocate_offset + aligned_numel]
        compute_tensor = swap_tensor[:numel]
        self.swap_tensors[allocate_offset] = swap_tensor
        self.compute_tensors[allocate_offset] = compute_tensor
        self.swap_paths[allocate_offset] = swap_path
        self.offset += aligned_numel
        self.num_elem += numel
        return swap_tensor, compute_tensor

    def has_space(self, numel: int) -> bool:
        return self.offset + numel <= self.buffer.size

    def get_swap_tensors(self) -> List[np.ndarray]:
        return list(self.swap_tensors.values())

    def get_swap_paths(self) -> List[str]:
        return list(self.swap_paths.values())

    def get_compute_tensors(self) -> List[np.ndarray]:
        return list(self.compute_tensors.values())

    def get_num_elem(self) -> int:
        return self.num_elem


class SwapBufferPool:
    """A group of SwapBuffers written/read as one unit (reference :96)."""

    def __init__(self, buffers: List[np.ndarray]):
        self.buffers = [SwapBuffer(b) for b in buffers]
        self.current_index = 0

    def reset(self) -> None:
        self.current_index = 0
        for buffer in self.buffers:
            buffer.reset()

    def allocate_tensor(self, numel: int, swap_path: str, aligned_numel: int):
        if self.has_space(aligned_numel):
            return self._get_current_buffer().allocate_tensor(swap_path, numel, aligned_numel)
        return None, None

    def insert_tensor(self, tensor: np.ndarray, swap_path: str, aligned_numel: int):
        if self.has_space(aligned_numel):
            return self._get_current_buffer().insert_tensor(tensor, swap_path, aligned_numel)
        return None, None

    def get_swap_tensors(self) -> List[np.ndarray]:
        return [t for b in self._get_used_buffers() for t in b.get_swap_tensors()]

    def get_swap_paths(self) -> List[str]:
        return [p for b in self._get_used_buffers() for p in b.get_swap_paths()]

    def get_compute_tensors(self) -> List[np.ndarray]:
        return [t for b in self._get_used_buffers() for t in b.get_compute_tensors()]

    def has_space(self, numel: int) -> bool:
        if self._get_current_buffer().has_space(numel):
            return True
        if self.current_index == len(self.buffers) - 1:
            return False
        self.current_index += 1
        return self._get_current_buffer().has_space(numel)

    def swap_out(self, aio_handle) -> None:
        swap_out_tensors(aio_handle, self.get_swap_tensors(), self.get_swap_paths())
        assert aio_handle.wait() >= 0

    def swap_in(self, aio_handle) -> None:
        swap_in_tensors(aio_handle, self.get_swap_tensors(), self.get_swap_paths())
        assert aio_handle.wait() >= 0

    def _get_current_buffer(self) -> SwapBuffer:
        return self.buffers[self.current_index]

    def _get_used_buffers(self) -> List[SwapBuffer]:
        return self.buffers[: self.current_index + 1]


class SwapBufferManager:
    """Fixed pool of equal-size buffers with alloc/free (reference :120)."""

    def __init__(self, num_elems: int, count: int, dtype=np.float32):
        self.num_elems = num_elems
        self.count = count
        self.dtype = np.dtype(dtype)
        self.all_buffers = [np.zeros(num_elems, dtype=self.dtype) for _ in range(count)]
        self.free_buffer_index = list(range(count))
        self.used_buffer_index: Dict[int, int] = {}
        self.gigabytes = (count * num_elems * self.dtype.itemsize) / 1024**3

    def allocate(self, num_elems: int, count: int, dtype=np.float32) -> Optional[List[np.ndarray]]:
        assert np.dtype(dtype) == self.dtype
        assert num_elems <= self.num_elems
        if count > len(self.free_buffer_index):
            return None
        buffers = []
        for _ in range(count):
            i = self.free_buffer_index.pop()
            buf = self.all_buffers[i][:num_elems]
            self.used_buffer_index[id(buf)] = i
            buffers.append(buf)
        return buffers

    def allocate_all(self, num_elems: int, dtype=np.float32) -> Optional[List[np.ndarray]]:
        return self.allocate(num_elems, len(self.free_buffer_index), dtype)

    def free(self, buffers: List[np.ndarray]) -> None:
        for buf in buffers:
            i = self.used_buffer_index.pop(id(buf), None)
            if i is None:
                logger.warning("SwapBufferManager.free: unknown buffer")
                continue
            self.free_buffer_index.append(i)


def get_sized_buffer(buffer: np.ndarray, num_elems: int) -> np.ndarray:
    assert num_elems <= buffer.size
    return buffer[:num_elems]


def get_sized_buffers(buffers: List[np.ndarray], num_elems_list: List[int]) -> List[np.ndarray]:
    return [get_sized_buffer(b, n) for b, n in zip(buffers, num_elems_list)]
