"""Async tensor swap-out pipeline.

Counterpart of the reference's ``AsyncTensorSwapper``
(``swap_tensor/async_swapper.py:18``): tensors are packed into a staging
buffer; when it fills, the buffer is flushed to disk asynchronously while a
fresh buffer keeps accepting tensors — overlapping disk writes with the
caller's compute.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.utils import SwapBuffer, swap_out_tensors
from deepspeed_tpu.utils.logging import logger

INVALID_BUFFER_INDEX = -1
ASYNC_SWAPPER_WAIT_TIMER = "async_swap_gradient_wait"


class AsyncTensorSwapper:
    def __init__(self, aio_handle, numel_alignment: int, timers=None):
        self.free_buffer_index: List[int] = []
        self.swapping_buffer_index = INVALID_BUFFER_INDEX
        self.ready_buffer_index = INVALID_BUFFER_INDEX
        self.current_buffer_index = INVALID_BUFFER_INDEX
        self.all_buffers: List[SwapBuffer] = []
        self.aio_handle = aio_handle
        self.numel_alignment = numel_alignment
        self.max_numel = 0
        self.num_pending_swaps = 0
        self.timers = timers
        self.swapped_tensors = 0
        self.swapped_bytes = 0

    def has_buffers(self) -> bool:
        return len(self.all_buffers) > 0

    def add_buffers(self, buffer_list: List[np.ndarray]) -> None:
        assert not self.all_buffers
        assert all(b.dtype == buffer_list[0].dtype for b in buffer_list)
        self.all_buffers = [SwapBuffer(b) for b in buffer_list]
        self.free_buffer_index = list(range(len(self.all_buffers)))
        self.max_numel = max(b.size for b in buffer_list)

    def get_timer_names(self) -> List[str]:
        return [ASYNC_SWAPPER_WAIT_TIMER]

    def release_buffers(self) -> List[np.ndarray]:
        self._report_statistics("Swapped out[Before flush]")
        self._flush_buffers_until_complete()
        self._report_statistics("Swapped out[After flush]")
        buffers = [b.buffer for b in self.all_buffers]
        self.all_buffers = []
        self.free_buffer_index = []
        self.swapped_tensors = 0
        self.swapped_bytes = 0
        return buffers

    def swap_out_tensors(self, tensor_list: List[np.ndarray], path_list: List[str]) -> None:
        for tensor, path in zip(tensor_list, path_list):
            self._swap_out_tensor(tensor, path)

    def _report_statistics(self, message: str) -> None:
        logger.debug(
            f"{message}: {self.swapped_tensors} tensors, "
            f"{self.swapped_bytes / 1024**3:.2f} GB"
        )

    def _swap_out_tensor(self, tensor: np.ndarray, swap_path: str) -> None:
        assert self.all_buffers, "add_buffers must be called first"
        aligned_numel = self._io_aligned_numel(tensor.size)
        assert aligned_numel <= self.max_numel, (
            f"tensor of {aligned_numel} elements exceeds buffer size {self.max_numel}"
        )
        self._make_swap_space(aligned_numel)
        swap_buffer = self.all_buffers[self.current_buffer_index]
        swap_buffer.insert_tensor(tensor.ravel(), swap_path, aligned_numel)
        self.swapped_tensors += 1
        self.swapped_bytes += tensor.nbytes

    def _make_swap_space(self, numel: int) -> None:
        if self.current_buffer_index == INVALID_BUFFER_INDEX:
            self._allocate_buffer()
            return
        if not self.all_buffers[self.current_buffer_index].has_space(numel):
            if self.free_buffer_index:
                self._flush_ready_buffers()
            else:
                self._flush_buffers_until_complete()
            self._allocate_buffer()

    def _io_aligned_numel(self, numel: int) -> int:
        remainder = numel % self.numel_alignment
        return numel if remainder == 0 else numel + self.numel_alignment - remainder

    def _allocate_buffer(self) -> None:
        assert self.free_buffer_index
        if self.current_buffer_index != INVALID_BUFFER_INDEX:
            # previous buffer becomes ready-to-flush
            self.ready_buffer_index = self.current_buffer_index
        self.current_buffer_index = self.free_buffer_index.pop()

    def _flush_ready_buffers(self) -> None:
        if self.current_buffer_index != INVALID_BUFFER_INDEX:
            self.ready_buffer_index = self.current_buffer_index
            self.current_buffer_index = INVALID_BUFFER_INDEX
        self._swap_out_ready_buffers()

    def _flush_buffers_until_complete(self) -> None:
        self._flush_ready_buffers()
        self._wait_for_swap_complete()

    def _swap_out_ready_buffers(self) -> None:
        if self.ready_buffer_index == INVALID_BUFFER_INDEX:
            return
        buffer = self.all_buffers[self.ready_buffer_index]
        swap_out_tensors(self.aio_handle, buffer.get_swap_tensors(), buffer.get_swap_paths())
        self.num_pending_swaps += len(buffer.get_swap_tensors())
        self.swapping_buffer_index = self.ready_buffer_index
        self.ready_buffer_index = INVALID_BUFFER_INDEX

    def _wait_for_swap_complete(self) -> None:
        if self.swapping_buffer_index == INVALID_BUFFER_INDEX:
            return
        if self.timers is not None:
            self.timers(ASYNC_SWAPPER_WAIT_TIMER).start()
        self.aio_handle.wait()
        if self.timers is not None:
            self.timers(ASYNC_SWAPPER_WAIT_TIMER).stop()
        self.num_pending_swaps = 0
        buffer = self.all_buffers[self.swapping_buffer_index]
        buffer.reset()
        self.free_buffer_index.append(self.swapping_buffer_index)
        self.swapping_buffer_index = INVALID_BUFFER_INDEX
