"""Optimizer-state swapper base.

Counterpart of the reference's ``OptimizerSwapper``
(``swap_tensor/optimizer_utils.py:112``): owns the file layout for each
parameter's optimizer-state tensors (master fp32 + moments) on the swap
device, the staging-buffer pool, and the swap-in/out of whole parameter
groups. Subclasses choose the overlap strategy.
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle
from deepspeed_tpu.runtime.swap_tensor.aio_config import AioConfig
from deepspeed_tpu.runtime.swap_tensor.utils import (
    MIN_AIO_BYTES,
    AIO_ALIGNED_BYTES,
    SwapBufferManager,
)
from deepspeed_tpu.utils.logging import logger


class SwapTensorInfo:
    """File-backed state tensors for one parameter (reference
    ``OptimizerStateSwapInfo`` optimizer_utils.py:37)."""

    def __init__(self, param_id: str, numel: int, swap_folder: str, state_names: List[str]):
        self.param_id = param_id
        self.numel = numel
        self.state_names = list(state_names)
        self.swap_paths = {
            name: os.path.join(swap_folder, f"{param_id}_{name}.tensor.swp")
            for name in state_names
        }
        self.swapped_out = False


class OptimizerSwapper:
    def __init__(
        self,
        swap_config,
        aio_config: AioConfig,
        base_folder: str,
        largest_numel: int,
        device_id: int = 0,
        dtype=np.float32,
    ):
        self.swap_config = swap_config
        self.aio_config = aio_config
        self.dtype = np.dtype(dtype)

        self.swap_folder = os.path.join(base_folder, "zero_stage_3", "optimizer", f"rank{device_id}")
        os.makedirs(self.swap_folder, exist_ok=True)

        self.min_aio_bytes = max(MIN_AIO_BYTES, aio_config.block_size)
        self.aligned_bytes = AIO_ALIGNED_BYTES * aio_config.thread_count
        self.numel_alignment = self.aligned_bytes // self.dtype.itemsize
        self.largest_numel = self._io_aligned_numel(largest_numel)

        buffer_count = getattr(swap_config, "buffer_count", 4)
        self.buffers = SwapBufferManager(
            num_elems=self.largest_numel, count=buffer_count, dtype=self.dtype
        )
        self.aio_handle = AsyncIOHandle(
            block_size=aio_config.block_size,
            queue_depth=aio_config.queue_depth,
            single_submit=aio_config.single_submit,
            overlap_events=aio_config.overlap_events,
            thread_count=aio_config.thread_count,
        )
        self.swap_params_info: Dict[str, SwapTensorInfo] = {}

    def purge_state(self) -> None:
        """Drop all swap files (fresh-start after checkpoint load)."""
        shutil.rmtree(self.swap_folder, ignore_errors=True)
        os.makedirs(self.swap_folder, exist_ok=True)
        self.swap_params_info.clear()

    def register_param(self, param_id: str, numel: int, state_names: List[str]) -> SwapTensorInfo:
        if param_id not in self.swap_params_info:
            self.swap_params_info[param_id] = SwapTensorInfo(
                param_id, numel, self.swap_folder, state_names
            )
        return self.swap_params_info[param_id]

    def swappable_tensor(self, numel: int) -> bool:
        return numel * self.dtype.itemsize >= self.min_aio_bytes

    def _io_aligned_numel(self, numel: int) -> int:
        remainder = numel % self.numel_alignment
        return numel if remainder == 0 else numel + self.numel_alignment - remainder

    # --- synchronous single-param swap primitives ------------------------
    def swap_out_param(self, param_id: str, state_tensors: Dict[str, np.ndarray]) -> None:
        info = self.swap_params_info[param_id]
        aligned = self._io_aligned_numel(info.numel)
        buffers = self.buffers.allocate(aligned, count=len(info.state_names), dtype=self.dtype)
        assert buffers is not None, "no free swap buffers"
        try:
            for buf, name in zip(buffers, info.state_names):
                src = state_tensors[name].ravel()
                buf[: src.size] = src
                self.aio_handle.async_pwrite(buf[:aligned], info.swap_paths[name])
            self.aio_handle.wait()
            info.swapped_out = True
        finally:
            self.buffers.free(buffers)

    def swap_in_param(self, param_id: str, out: Dict[str, np.ndarray]) -> None:
        info = self.swap_params_info[param_id]
        assert info.swapped_out, f"param {param_id} has no swapped state"
        aligned = self._io_aligned_numel(info.numel)
        buffers = self.buffers.allocate(aligned, count=len(info.state_names), dtype=self.dtype)
        assert buffers is not None, "no free swap buffers"
        try:
            for buf, name in zip(buffers, info.state_names):
                self.aio_handle.async_pread(buf[:aligned], info.swap_paths[name])
            self.aio_handle.wait()
            for buf, name in zip(buffers, info.state_names):
                out[name][:] = buf[: info.numel].reshape(out[name].shape)
        finally:
            self.buffers.free(buffers)

    def log_statistics(self) -> None:
        n = len(self.swap_params_info)
        total = sum(i.numel * len(i.state_names) for i in self.swap_params_info.values())
        logger.info(
            f"OptimizerSwapper: {n} params, "
            f"{total * self.dtype.itemsize / 1024**3:.2f} GB on {self.swap_folder}"
        )
