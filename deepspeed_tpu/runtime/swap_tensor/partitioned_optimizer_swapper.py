"""Partitioned optimizer swapper with prefetch pipelining.

Counterpart of the reference's ``PartitionedOptimizerSwapper``
(``swap_tensor/partitioned_optimizer_swapper.py:28``) and the
double-buffered ``PipelinedOptimizerSwapper``
(``pipelined_optimizer_swapper.py:51``) collapsed into one class: while the
host optimizer updates parameter group *i*, group *i+1*'s state reads are
already in flight on a second aio handle, and group *i-1*'s writes drain on
a third — the swap latency hides behind the AVX Adam update.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle
from deepspeed_tpu.runtime.swap_tensor.optimizer_utils import OptimizerSwapper
from deepspeed_tpu.utils.logging import logger


class PartitionedOptimizerSwapper(OptimizerSwapper):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        aio = self.aio_config
        # dedicated handles so reads/writes/prefetch overlap independently
        self._read_handle = AsyncIOHandle(
            block_size=aio.block_size,
            queue_depth=aio.queue_depth,
            single_submit=aio.single_submit,
            overlap_events=aio.overlap_events,
            thread_count=aio.thread_count,
        )
        self._write_handle = AsyncIOHandle(
            block_size=aio.block_size,
            queue_depth=aio.queue_depth,
            single_submit=aio.single_submit,
            overlap_events=aio.overlap_events,
            thread_count=aio.thread_count,
        )
        self._prefetch_buffers: Optional[List[np.ndarray]] = None
        self._prefetch_param: Optional[str] = None
        self._pending_write_buffers: Optional[List[np.ndarray]] = None

    # --- pipelined API ----------------------------------------------------
    def prefetch_param(self, param_id: str) -> None:
        """Begin async swap-in of the NEXT param's state (double buffer)."""
        if self._prefetch_param is not None:
            return
        info = self.swap_params_info.get(param_id)
        if info is None or not info.swapped_out:
            return
        aligned = self._io_aligned_numel(info.numel)
        buffers = self.buffers.allocate(aligned, count=len(info.state_names), dtype=self.dtype)
        if buffers is None:
            return  # pool exhausted; fall back to sync path on fetch
        for buf, name in zip(buffers, info.state_names):
            self._read_handle.async_pread(buf[:aligned], info.swap_paths[name])
        self._prefetch_buffers = buffers
        self._prefetch_param = param_id

    def fetch_param(self, param_id: str, out: Dict[str, np.ndarray]) -> None:
        """Complete a prefetch (or do a sync swap-in) into ``out``."""
        info = self.swap_params_info[param_id]
        if self._prefetch_param == param_id:
            self._read_handle.wait()
            buffers = self._prefetch_buffers
            for buf, name in zip(buffers, info.state_names):
                out[name][:] = buf[: info.numel].reshape(out[name].shape)
            self.buffers.free(buffers)
            self._prefetch_param = None
            self._prefetch_buffers = None
            return
        if self._prefetch_param is not None:
            # mispredicted prefetch: drain and drop it
            logger.debug(
                f"swap prefetch of {self._prefetch_param} unused; fetching {param_id}"
            )
            self._read_handle.wait()
            self.buffers.free(self._prefetch_buffers)
            self._prefetch_param = None
            self._prefetch_buffers = None
        self.swap_in_param(param_id, out)

    def writeback_param(self, param_id: str, state_tensors: Dict[str, np.ndarray]) -> None:
        """Async swap-out of updated state; previous writeback is drained
        first (one write generation in flight)."""
        self.drain_writes()
        info = self.swap_params_info[param_id]
        aligned = self._io_aligned_numel(info.numel)
        buffers = self.buffers.allocate(aligned, count=len(info.state_names), dtype=self.dtype)
        if buffers is None:
            self.swap_out_param(param_id, state_tensors)
            return
        for buf, name in zip(buffers, info.state_names):
            src = state_tensors[name].ravel()
            buf[: src.size] = src
            self._write_handle.async_pwrite(buf[:aligned], info.swap_paths[name])
        info.swapped_out = True
        self._pending_write_buffers = buffers

    def drain_writes(self) -> None:
        if self._pending_write_buffers is not None:
            self._write_handle.wait()
            self.buffers.free(self._pending_write_buffers)
            self._pending_write_buffers = None
