"""Legacy sharded torch state-dict loading for inference.

Counterpart of the reference's ``deepspeed/runtime/state_dict_factory.py``
(``SDLoaderFactory`` :21, ``SDLoaderBase.load`` :57, ``MegatronSDLoader``
:190): Megatron ``SplitCheckpoint`` file lists merged down or split up to a
target mp degree at load time, with optional quantize-on-load
(``weight_quantizer.WeightQuantization``).

TPU-native shape: everything is numpy on the host — the merged result is a
FULL state dict handed to a ``module_inject`` container policy, which builds
the global param tree that GSPMD then shards; per-rank torch tensors never
exist. Merging to mp_world_size=1 is therefore the common path here, but
arbitrary merge/split parity (including the three historical Megatron QKV
packings) is kept so ds-inference checkpoint descriptors load unchanged.
"""

from __future__ import annotations

import json
import os
from abc import ABC, abstractmethod
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
from deepspeed_tpu.utils.logging import logger

AUTO_MODULE_KEY = "auto"


def _torch_to_numpy_tree(sd):
    """Torch tensors → numpy at the file boundary; containers/policies and
    the quantizer all speak numpy."""
    out = OrderedDict()
    for k, v in sd.items():
        if hasattr(v, "detach"):
            v = v.detach().cpu()
            if str(getattr(v, "dtype", "")) == "torch.bfloat16":
                v = v.float()
            v = v.numpy()
        out[k] = v
    return out


class SDLoaderFactory:
    """(reference state_dict_factory.py:21)"""

    @staticmethod
    def get_sd_loader_json(json_file, checkpoint_engine=None):
        if isinstance(json_file, str):
            with open(json_file) as f:
                data = json.load(f)
        else:
            assert isinstance(json_file, dict)
            data = json_file
        sd_type = data["type"]
        ckpt_list = data["checkpoints"]
        version = data.get("version")
        if sd_type.lower() in ("bloom", "ds_model"):
            return data  # pre-sharded ds-inference layouts pass through
        return SDLoaderFactory.get_sd_loader(ckpt_list, checkpoint_engine, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list, checkpoint_engine=None, sd_type: str = "Megatron", version=None):
        if sd_type == "Megatron":
            return MegatronSDLoader(ckpt_list, version, checkpoint_engine)
        raise ValueError(f"{sd_type} checkpoint type is not supported")


class SDLoaderBase(ABC):
    """(reference :47) — ``load`` returns ``(path, sd, (scales, merge_count))``."""

    def __init__(self, ckpt_list: List[str], version, checkpoint_engine=None):  # noqa: ARG002
        self.module_key: Optional[str] = None
        self.ckpt_list = list(ckpt_list)
        self.version = version
        self._first_sd: Optional[Dict[str, Any]] = None
        self.check_ckpt_list()

    def _load_file(self, path: str) -> Dict[str, Any]:
        # shard 0 is read by check_ckpt_list, sanity_check AND the merge
        # itself — cache it (shallow copy out, so set_module/quantize on one
        # load() can't leak into the next)
        if self._first_sd is not None and path == self.ckpt_list[0]:
            return dict(self._first_sd)
        import torch

        sd = torch.load(path, map_location="cpu", weights_only=False)
        if path == self.ckpt_list[0]:
            self._first_sd = sd
            return dict(sd)
        return sd

    def load(
        self,
        mp_world_size: int,
        mp_rank: int,
        module_key: str = AUTO_MODULE_KEY,
        is_pipe_parallel: bool = False,
        quantize: bool = False,
        quantize_bits: int = 8,
        quantize_groups: int = 64,
        mlp_extra_grouping: bool = True,
    ):
        self.module_key = module_key
        num_ckpt = len(self.ckpt_list)
        idx = mp_rank * num_ckpt // mp_world_size
        if is_pipe_parallel and module_key is not None and mp_world_size != num_ckpt:
            # pipe-resized: each mp_rank file repeats the content; read 0
            mp_world_size = num_ckpt
            idx = 0
        load_path = self.ckpt_list[idx]
        merge_count = 1
        if num_ckpt == mp_world_size:
            sd = self._load_file(load_path)
            if quantize:
                quantizer = WeightQuantization(
                    mlp_extra_grouping=mlp_extra_grouping, mp_size=mp_world_size
                )
                sd_module, all_scales = quantizer.sd_quantize_megatron(
                    _torch_to_numpy_tree(self.get_module(sd)), quantize_bits, quantize_groups
                )
                sd = self.set_module(sd, sd_module)
            else:
                # numpy at the boundary on EVERY path (the merge/split
                # branches already convert): downstream policies np.asarray
                # leaves, which raises on torch bf16 tensors
                sd = self.set_module(sd, _torch_to_numpy_tree(self.get_module(sd)))
                all_scales = None
        elif num_ckpt > mp_world_size:
            sd, all_scales, merge_count = self.merge_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits, quantize_groups, mlp_extra_grouping
            )
        else:
            sd, all_scales = self.split_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits, quantize_groups, mlp_extra_grouping
            )
        return load_path, sd, (all_scales, merge_count)

    def get_merge_state_dicts(self, mp_world_size: int, mp_rank: int):
        num_ckpt = len(self.ckpt_list)
        assert num_ckpt % mp_world_size == 0, "Invalid checkpoints and world size for sd merge"
        num_to_merge = num_ckpt // mp_world_size
        files = self.ckpt_list[num_to_merge * mp_rank : num_to_merge * (mp_rank + 1)]
        logger.info(f"mp_rank: {mp_rank}, ckpt_list: {files}")
        return [self._load_file(f) for f in files]

    def get_split_state_dict(self, mp_world_size: int, mp_rank: int):
        num_ckpt = len(self.ckpt_list)
        assert mp_world_size % num_ckpt == 0, "Invalid checkpoints and world size for sd split"
        num_to_split = mp_world_size // num_ckpt
        ckpt_index = mp_rank // num_to_split
        ckpt_offset = mp_rank % num_to_split
        logger.info(
            f"mp_rank: {mp_rank}, ckpt_list: {self.ckpt_list[ckpt_index]}, offset: {ckpt_offset}"
        )
        return self._load_file(self.ckpt_list[ckpt_index]), num_to_split, ckpt_offset

    def _choose_module_key(self, sd) -> str:
        assert not ("module" in sd and "model" in sd), (
            "checkpoint has both 'model' and 'module' keys, not sure how to proceed"
        )
        assert "module" in sd or "model" in sd, (
            "checkpoint contains neither 'model' or 'module' keys, not sure how to proceed"
        )
        return "module" if "module" in sd else "model"

    def get_module(self, sd):
        if self.module_key is None:
            return sd
        if self.module_key == AUTO_MODULE_KEY:
            return sd[self._choose_module_key(sd)]
        return sd[self.module_key]

    def set_module(self, sd, module):
        if self.module_key is None:
            sd = module
        elif self.module_key == AUTO_MODULE_KEY:
            sd[self._choose_module_key(sd)] = module
        else:
            sd[self.module_key] = module
        return sd

    def check_ckpt_list(self) -> None:
        assert len(self.ckpt_list) > 0
        sd = self._load_file(self.ckpt_list[0])
        if "mp_world_size" in sd:
            assert len(self.ckpt_list) == sd["mp_world_size"], (
                f"checkpoint count {len(self.ckpt_list)} is different from "
                f"saved mp_world_size {sd['mp_world_size']}"
            )

    @abstractmethod
    def merge_state_dict(self, mp_world_size, mp_rank, quantize, quantize_bits, groups, mlp_extra_grouping):
        ...

    @abstractmethod
    def split_state_dict(self, mp_world_size, mp_rank, quantize, quantize_bits, groups, mlp_extra_grouping):
        ...

    @abstractmethod
    def sanity_check(self, ckpt_file_name: str):
        ...


class MegatronSDLoader(SDLoaderBase):
    """(reference :190) Megatron SplitCheckpoint merge/split with the three
    historical QKV packings."""

    def merge_query_key_value(self, param_list: List[np.ndarray], ckpt_ver) -> np.ndarray:
        """(reference :220) version 0: [(3*np*hn), h] interleaves q/k/v per
        shard — regroup before concat; 1.0/2.0: plain concat."""
        if ckpt_ver == 0:
            assert param_list[0].shape[0] % 3 == 0
            split_tensors = [np.split(p, 3, axis=0) for p in param_list]
            tensors = [
                np.concatenate([t[i] for t in split_tensors], axis=0) for i in range(3)
            ]
            return np.concatenate(tensors, axis=0)
        if ckpt_ver in (1.0, 2.0):
            return np.concatenate(param_list, axis=0)
        raise ValueError(f"checkpoint version: {ckpt_ver} is not supported")

    def split_query_key_value(self, param: np.ndarray, num_to_split: int, offset: int, ckpt_ver) -> np.ndarray:
        """(reference :258)"""
        if ckpt_ver == 0:
            assert param.shape[0] % 3 == 0
            split_tensors = np.split(param, 3, axis=0)
            assert split_tensors[0].shape[0] % num_to_split == 0
            return np.concatenate(
                [np.split(t, num_to_split, axis=0)[offset] for t in split_tensors], axis=0
            )
        if ckpt_ver in (1.0, 2.0):
            assert param.shape[0] % num_to_split == 0
            return np.split(param, num_to_split, axis=0)[offset]
        raise ValueError(f"checkpoint version: {ckpt_ver} is not supported")

    def merge_state_dict(
        self, mp_world_size, mp_rank, quantize=False, quantize_bits=8, groups=64, mlp_extra_grouping=True
    ):
        self.sanity_check(self.ckpt_list[0])
        sd_list = self.get_merge_state_dicts(mp_world_size, mp_rank)
        ds_sd = dict(sd_list[0])
        client_sd_list = [_torch_to_numpy_tree(self.get_module(sd)) for sd in sd_list]
        keys = client_sd_list[0].keys()
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        logger.info(f"checkpoint version: {ckpt_ver}")
        quantizer = (
            WeightQuantization(mlp_extra_grouping=mlp_extra_grouping, mp_size=mp_world_size)
            if quantize
            else None
        )
        new_client_sd = OrderedDict()
        for key in keys:
            value_list = [sd[key] for sd in client_sd_list]
            if "attention.dense.weight" in key or "mlp.dense_4h_to_h.weight" in key:
                if quantize:
                    value_list = quantizer.Quantize(
                        value_list, quantize_bits, groups, key=key, merge_dim=1
                    )
                new_client_sd[key] = np.concatenate(value_list, axis=1)
            elif "attention.query_key_value" in key:
                if quantize and "attention.query_key_value.weight" in key:
                    value_list = quantizer.Quantize(value_list, quantize_bits, groups, key=key)
                    new_client_sd[key] = np.concatenate(value_list, axis=0)
                else:
                    new_client_sd[key] = self.merge_query_key_value(value_list, ckpt_ver)
            elif (
                "mlp.dense_h_to_4h.weight" in key
                or "word_embeddings.weight" in key
                or "mlp.dense_h_to_4h.bias" in key
            ):
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    value_list = quantizer.Quantize(value_list, quantize_bits, groups, key=key)
                new_client_sd[key] = np.concatenate(value_list, axis=0)
            else:
                new_client_sd[key] = value_list[0]
        all_scales = quantizer.merge_scales() if quantize else None
        ds_sd = self.set_module(ds_sd, new_client_sd)
        return ds_sd, all_scales, len(client_sd_list)

    def split_state_dict(
        self, mp_world_size, mp_rank, quantize=False, quantize_bits=8, groups=64, mlp_extra_grouping=True
    ):
        sd, num_to_split, ckpt_offset = self.get_split_state_dict(mp_world_size, mp_rank)
        ds_sd = dict(sd)
        client_sd = _torch_to_numpy_tree(self.get_module(sd))
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        logger.info(f"checkpoint version: {ckpt_ver}")
        quantizer = (
            WeightQuantization(mlp_extra_grouping=mlp_extra_grouping, mp_size=mp_world_size)
            if quantize
            else None
        )
        new_client_sd = OrderedDict()
        for key, value in client_sd.items():
            if "attention.dense.weight" in key or "mlp.dense_4h_to_h.weight" in key:
                assert value.shape[1] % num_to_split == 0
                if quantize:
                    value = quantizer.Quantize([value], quantize_bits, groups, key=key)[0]
                new_client_sd[key] = np.split(value, num_to_split, axis=1)[ckpt_offset]
            elif "attention.query_key_value" in key:
                if quantize and "attention.query_key_value.weight" in key:
                    value = quantizer.Quantize([value], quantize_bits, groups, key=key)[0]
                new_client_sd[key] = self.split_query_key_value(
                    value, num_to_split, ckpt_offset, ckpt_ver
                )
            elif (
                "mlp.dense_h_to_4h.weight" in key
                or "word_embeddings.weight" in key
                or "mlp.dense_h_to_4h.bias" in key
                or "final_linear.weight" in key
            ):
                assert value.shape[0] % num_to_split == 0
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    value = quantizer.Quantize([value], quantize_bits, groups, key=key)[0]
                new_client_sd[key] = np.split(value, num_to_split, axis=0)[ckpt_offset]
            else:
                new_client_sd[key] = value
        all_scales = quantizer.merge_scales_split(num_to_split) if quantize else None
        ds_sd = self.set_module(ds_sd, new_client_sd)
        return ds_sd, all_scales

    def sanity_check(self, ckpt_file_name: str) -> None:
        keys_to_check = [
            "attention.dense.weight",
            "mlp.dense_4h_to_h.weight",
            "attention.query_key_value",
            "mlp.dense_h_to_4h.weight",
            "mlp.dense_h_to_4h.bias",
        ]
        sd = self._load_file(ckpt_file_name)
        module = self.get_module(sd)
        for key in keys_to_check:
            assert any(key in k for k in module.keys()), (
                f"key: {key} is not found in the checkpoint {ckpt_file_name}"
            )

    def get_checkpoint_version(self, state_dict):
        return (
            self.version if self.version is not None else state_dict.get("checkpoint_version", 0)
        )
