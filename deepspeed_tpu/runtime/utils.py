"""Runtime utilities (reference: ``deepspeed/runtime/utils.py``).

The MP-aware grad clipping lives inside the jitted step
(``engine.update_from_grads``); this module carries the user-facing
surfaces: ``see_memory_usage`` (device + host memory report),
``CheckOverflow`` (grad-overflow scan), ``clip_grad_norm_`` (functional,
global-norm over a grad tree), and the ZeRO memory estimators re-exported
from the partitioner.
"""

from __future__ import annotations

import gc
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.zero.partition import estimate_zero_memory
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "see_memory_usage",
    "CheckOverflow",
    "clip_grad_norm_",
    "global_grad_norm",
    "estimate_zero_memory",
    "call_to_str",
]


def _device_memory_stats() -> dict:
    """Per-device HBM stats where the backend exposes them (TPU does);
    falls back to summing live jax.Array footprints."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            return {
                "bytes_in_use": stats.get("bytes_in_use", 0),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
                "bytes_limit": stats.get("bytes_limit", 0),
            }
    except Exception:
        pass
    live = 0
    for arr in jax.live_arrays():
        live += arr.size * arr.dtype.itemsize
    return {"bytes_in_use": live, "peak_bytes_in_use": 0, "bytes_limit": 0}


def see_memory_usage(message: str, force: bool = False) -> Optional[dict]:
    """Log device HBM + host RAM usage (reference ``see_memory_usage``).
    Returns the stats dict (handy for tests); None when not forced."""
    if not force:
        return None
    from deepspeed_tpu import comm as dist

    if dist.is_initialized() and dist.get_rank() != 0:
        return None
    gc.collect()
    dev = _device_memory_stats()
    GB = 1024**3
    logger.info(message)
    logger.info(
        f"MA {dev['bytes_in_use'] / GB:.2f} GB  "
        f"Max_MA {dev['peak_bytes_in_use'] / GB:.2f} GB  "
        f"Limit {dev['bytes_limit'] / GB:.2f} GB"
    )
    try:
        import psutil

        vm = psutil.virtual_memory()
        used_gb = (vm.total - vm.available) / GB
        logger.info(f"CPU Virtual Memory:  used = {used_gb:.2f} GB, percent = {vm.percent}%")
        dev["host_used_bytes"] = vm.total - vm.available
    except ImportError:
        pass
    return dev


def global_grad_norm(grads: Any) -> jnp.ndarray:
    """Global L2 norm over a grad pytree. Full reductions over sharded
    leaves are global under GSPMD — no explicit MP all-reduce needed
    (the reference's mpu-aware ``get_grad_norm``)."""
    sq = sum(
        jnp.sum(jnp.square(g.astype(jnp.float32)))
        for g in jax.tree_util.tree_leaves(grads)
    )
    return jnp.sqrt(sq)


def clip_grad_norm_(grads: Any, max_norm: float, norm: Optional[jnp.ndarray] = None):
    """Functional grad clipping: returns (clipped_grads, global_norm)
    (reference ``clip_grad_norm_``, which mutates; pytrees are immutable)."""
    total = global_grad_norm(grads) if norm is None else norm
    coef = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * coef, grads), total


class CheckOverflow:
    """Grad-overflow scan (reference ``CheckOverflow``). Under GSPMD a full
    reduction over sharded grads is already global, so the reference's
    cross-process all-reduces collapse into the jnp reductions."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False, deepspeed=None):  # noqa: ARG002
        self.params = param_groups

    @staticmethod
    def has_overflow(grads: Any) -> bool:
        from deepspeed_tpu.runtime.fp16.loss_scaler import has_inf_or_nan

        if grads is None:
            return False
        return bool(jax.device_get(has_inf_or_nan(grads)))

    @staticmethod
    def check_using_norm(norm_group: Sequence[float]) -> bool:
        """-1 in a norm group marks an overflowed partition (reference
        semantics)."""
        arr = np.asarray(list(norm_group), dtype=np.float64)
        return bool((arr == -1).any() or ~np.isfinite(arr).all())

    def check(self, param_groups=None) -> bool:
        groups = param_groups if param_groups is not None else self.params
        return self.has_overflow(groups)


def call_to_str(base: str, *args, **kwargs) -> str:
    """'fn(a, b, k=v)' debug formatting (reference ``call_to_str``)."""
    name = f"{base}("
    if args:
        name += ", ".join(str(arg) for arg in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v}" for k, v in kwargs.items())
    return name + ")"
