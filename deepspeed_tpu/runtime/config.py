"""Main config: JSON/dict → ``DeepSpeedConfig``.

Counterpart of the reference's ``deepspeed/runtime/config.py`` (batch-triad
resolution, per-feature accessors) with the pydantic section models of
``config_utils.py``. One TPU-native addition: a ``mesh`` section declaring the
logical device-mesh axis sizes (data/model/sequence/expert/pipe); ``data`` is
derived from the device count when left auto, matching the reference's
"dp = world // (mp*pp)" derivation (``deepspeed/utils/groups.py``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Union

from pydantic import Field, field_validator, model_validator

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (
    DeepSpeedConfigModel,
    ScientificNotationEncoder,
    dict_raise_error_on_duplicate_keys,
)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig, ZeroStageEnum
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    # fp32 grad accumulation across micro-batches (reference bf16_optimizer)
    immediate_grad_update: bool = False


class OptimizerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class MeshConfig(DeepSpeedConfigModel):
    """Logical device mesh axis sizes. 0/None = derive.

    Axis names follow the scaling-book convention: data (DP/ZeRO), model (TP),
    sequence (Ulysses SP), expert (MoE EP), pipe (PP).
    """

    data: int = 0
    # MiCS replication axis: ZeRO shards over `data` only and replicates
    # across `data_outer` groups (reference deepspeed/runtime/zero/mics.py —
    # shard groups smaller than world). Total DP = data_outer × data.
    data_outer: int = 1
    model: int = 1
    sequence: int = 1
    expert: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        fixed = self.model * self.sequence * self.expert * self.pipe * self.data_outer
        if fixed <= 0 or n_devices % fixed != 0:
            raise DeepSpeedConfigError(
                f"mesh axes data_outer×model×sequence×expert×pipe={fixed} do not divide device count {n_devices}"
            )
        data = self.data or n_devices // fixed
        if data * fixed != n_devices:
            raise DeepSpeedConfigError(
                f"mesh {data}×{fixed} != device count {n_devices}"
            )
        return MeshConfig(
            data=data,
            data_outer=self.data_outer,
            model=self.model,
            sequence=self.sequence,
            expert=self.expert,
            pipe=self.pipe,
        )


def split_data_axis(mc: "MeshConfig", group_size: int, n_devices: int, feature: str) -> None:
    """Split the data axis into data(inner) × data_outer so ZeRO shards
    within groups of ``group_size`` ranks and replicates across groups.
    Shared by MiCS (``mics_shard_size``) and ZeRO++ hpZ
    (``zero_hpz_partition_size``). ``group_size`` counts ALL sharding ranks,
    so expert×sequence (always inside the group) divide it first. A mesh the
    user already split explicitly must agree with the requested group size."""
    fixed = mc.model * mc.sequence * mc.expert * mc.pipe
    inner_fixed = mc.expert * mc.sequence
    if group_size % inner_fixed != 0:
        raise ValueError(
            f"{feature}={group_size} must be a multiple of expert×sequence={inner_fixed} "
            "(those axes are always inside the shard group)"
        )
    data_inner = group_size // inner_fixed
    if mc.data_outer > 1:
        if mc.data != data_inner:
            raise ValueError(
                f"{feature}={group_size} (data slice {data_inner}) conflicts with the "
                f"explicitly split mesh (data={mc.data}, data_outer={mc.data_outer})"
            )
        return
    data_total = mc.data or (n_devices // fixed // mc.data_outer)
    if data_inner <= 0 or data_total % data_inner != 0:
        raise ValueError(
            f"{feature}={group_size} (data slice {data_inner}) does not divide "
            f"the data axis {data_total}"
        )
    mc.data = data_inner
    mc.data_outer = data_total // data_inner


class MultiStepTrainConfig(DeepSpeedConfigModel):
    """N-step fused training windows (``compile.multi_step``; ISSUE 14).

    ``enable`` arms the training-side twin of the serving multi-step
    windows (``paged_kv.multi_step``): when ``train_batch(data_iter=...)``
    sits at an optimizer-step boundary with ``horizon`` steps of data
    available and no schedule event (checkpoint interval, monitor flush,
    flops-profiler step) inside the window, the engine dispatches ONE
    jitted program that ``lax.scan``s ``horizon`` FULL optimizer steps —
    stacked ``[N, gas, ...]`` microbatches, per-step lr values evaluated
    ahead on the host and riding in as an array, fp16 dynamic loss-scale
    state carried through the scan so overflow-skip/rescale stays
    in-program — amortizing every per-step host cost (dispatch RTT, data
    fetch, h2d, loss fetch) to 1/N. Windows are bit-identical to N
    sequential ``train_batch`` calls by construction; any step a window
    cannot cover falls back to the single-step fused path and
    ``engine.window_stats()['window_break_reasons']`` says why.
    ``prefetch`` stages the next window's batches (sharded ``device_put``
    enqueued ahead) while the current window computes — the
    double-buffered input pipeline (``runtime/dataloader.py``
    ``PrefetchingLoader``; exact-resume data cursors are preserved).
    With ``gradient_accumulation_steps > 1`` the window scans the fused
    grad-accum step body, so ``compile.fuse_grad_accum`` must be on."""

    enable: bool = False
    horizon: int = 8
    prefetch: bool = True

    @model_validator(mode="after")
    def _check(self):
        if self.enable and self.horizon < 2:
            raise ValueError(
                "compile.multi_step.horizon must be >= 2 when enabled "
                "(1 is the single-step fused path)"
            )
        return self


class CompileConfig(DeepSpeedConfigModel):
    """TPU-native compile controls.

    ``fuse_grad_accum`` collapses a gas>1 optimizer step into ONE jitted
    program — a ``lax.scan`` over the stacked microbatches running
    fwd+bwd+accumulate, followed by the optimizer update — so the host
    dispatches once per optimizer step instead of gas+1 times (engaged
    through ``train_batch``; the per-microbatch forward/backward/step
    protocol keeps the unfused programs). ``multi_step`` goes one level
    further and fuses N whole optimizer steps into one dispatch (see
    :class:`MultiStepTrainConfig`). ``cache_dir`` opts into JAX's
    persistent compilation cache so repeated runs skip cold compiles;
    ``cache_min_compile_secs`` is the write threshold (0 caches everything).
    """

    fuse_grad_accum: bool = False
    multi_step: MultiStepTrainConfig = Field(default_factory=MultiStepTrainConfig)
    cache_dir: Optional[str] = None
    cache_min_compile_secs: float = 0.0


class AnalysisConfig(DeepSpeedConfigModel):
    """Static program-analysis controls (``deepspeed_tpu/analysis``).

    ``verify`` runs the program passes (donation-aliasing, dtype-promotion,
    host-transfer, collective budget) against each engine program right
    after its first compile: ``"warn"`` logs findings, ``"raise"`` fails
    fast on error-severity violations, ``"off"`` (default) leaves analysis
    on-demand via ``engine.analysis_report()``. ``passes`` narrows the pass
    list (empty = all). ``min_donation_bytes`` demotes unhonored donations
    smaller than the threshold to warnings (XLA legitimately skips aliasing
    tiny buffers on some backends). ``collective_budget_bytes`` turns the
    collective extractor into a gate: any single program whose static
    per-device collective payload exceeds the budget is a violation.
    Verification re-traces and re-compiles each program once — pair it with
    ``compile.cache_dir`` to make the second compile a cache hit.
    """

    verify: str = "off"  # off | warn | raise
    passes: List[str] = Field(default_factory=list)
    min_donation_bytes: int = 0
    collective_budget_bytes: Optional[int] = None
    # ZeRO-Infinity stream gate: budget for the DECLARED per-step offload
    # H2D+D2H stream bytes (overlap pass stream-accounting mode). None = no
    # budget; any declared traffic above it is an error-severity violation.
    stream_budget_bytes: Optional[int] = None
    # Static HBM gate: per-chip byte budget for the residency ledger
    # (``engine.memory_report()``) AND the memory pass's per-program peak
    # estimate. None = report-only. ``hbm_budget`` picks the reaction like
    # ``verify``: "raise" (default) fails with per-buffer attribution,
    # "warn" logs it, "off" disables the gate but keeps the ledger.
    hbm_budget_bytes: Optional[int] = None
    hbm_budget: str = "raise"  # off | warn | raise

    @field_validator("verify")
    @classmethod
    def _check_verify(cls, v):
        if v not in ("off", "warn", "raise"):
            raise ValueError(f"analysis.verify must be off|warn|raise, got {v!r}")
        return v

    @field_validator("hbm_budget")
    @classmethod
    def _check_hbm_budget(cls, v):
        if v not in ("off", "warn", "raise"):
            raise ValueError(
                f"analysis.hbm_budget must be off|warn|raise, got {v!r}"
            )
        return v


class TracingConfig(DeepSpeedConfigModel):
    """Unified tracing/metrics plane (``profiling/tracer.py``; ISSUE 10).

    ``enabled`` (default ON — the tracer is host-side only, adds zero
    device transfers and zero compiled programs, and measures under 2%
    of a bench step) records step-phase spans and engine metrics into a
    ``max_spans``-deep ring buffer, readable via ``engine.observability()``
    and exportable as a Perfetto/Chrome trace. ``flight_recorder`` arms the
    crash postmortem: on interpreter exit and on every ``utils/chaos.py``
    fault injection the last ``flight_recorder_spans`` spans + a metrics
    snapshot are dumped to ``flight_recorder_dir`` (required when armed)."""

    enabled: bool = True
    max_spans: int = 4096
    flight_recorder: bool = False
    flight_recorder_dir: Optional[str] = None
    flight_recorder_spans: int = 256

    @model_validator(mode="after")
    def _check_recorder(self):
        if self.flight_recorder and not self.flight_recorder_dir:
            raise ValueError(
                "tracing.flight_recorder requires tracing.flight_recorder_dir "
                "(the postmortem dump target)"
            )
        return self


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class CommsConfig(DeepSpeedConfigModel):
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)

    @property
    def comms_logger_enabled(self) -> bool:
        return self.comms_logger.enabled


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: the jax.checkpoint policy name to apply to each block
    policy: str = "nothing_saveable"


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class JSONLConfig(DeepSpeedConfigModel):
    """The torch-free always-available monitor backend: one JSON line per
    event under ``output_path/job_name/events.jsonl`` (append-only — torn
    tails are tolerated by line-wise readers). Default-ON whenever the
    ``monitor`` block is enabled; rank-0 gated like every backend."""

    enabled: bool = True
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class MonitorConfig(DeepSpeedConfigModel):
    """The ``monitor`` config block (reference ``deepspeed/monitor/config.py``
    + ``monitor.py:29`` MonitorMaster fanout).

    ``enabled`` is the master switch: it turns on the torch-free JSONL
    backend (rank 0) by default and lets the engine feed periodic metric
    events from the observability hub every ``interval_steps`` optimizer
    steps (0 = the ``steps_per_print`` cadence). TensorBoard / W&B / CSV
    remain individually opt-in (optional imports, degrade to disabled) and
    keep working from their legacy top-level config keys."""

    enabled: bool = False
    interval_steps: int = 0
    jsonl: JSONLConfig = Field(default_factory=JSONLConfig)
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)

    @property
    def active(self) -> bool:
        """Any path that produces events: the master switch (JSONL default)
        or a legacy individually-enabled backend."""
        return (
            self.enabled
            or self.tensorboard.enabled
            or self.wandb.enabled
            or self.csv_monitor.enabled
        )


class CheckpointConfig(DeepSpeedConfigModel):
    """Checkpoint controls. The fault-tolerance knobs (ISSUE 9):

    ``async_snapshot`` hides checkpoint persistence behind training compute
    — ``save_checkpoint`` snapshots the donated state tuple device→host
    (the only on-step cost, recorded as ``ckpt_stall_ms``) and a background
    writer runs the staged atomic save + commit + ``latest`` update
    (``checkpoint_engine/async_snapshot.py``). ``interval_steps`` > 0 with
    ``save_dir`` set auto-saves every N optimizer steps from inside the
    step bookkeeping, so a preempted run resumes via
    ``load_checkpoint(save_dir, auto_resume=True)`` losing at most N-1
    steps — and, because the payload carries the full replay state (RNG
    key, data cursor, loss scale, counters, LR schedule), losing ZERO
    information: the resumed losses are bit-identical to an uninterrupted
    run. ``max_inflight_snapshots`` bounds host RAM at that many state
    copies (double-buffered by default)."""

    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    # fault tolerance -----------------------------------------------------
    async_snapshot: bool = False
    interval_steps: int = 0  # 0 = no auto-save
    save_dir: Optional[str] = None  # auto-save target (required for interval)
    max_inflight_snapshots: int = 2


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class AMPConfig(DeepSpeedConfigModel):
    enabled: bool = False
    opt_level: str = "O1"


class GradientCompressionConfig(DeepSpeedConfigModel):
    enabled: bool = False


class HybridEngineConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class EigenvalueConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0


class PLDConfig(DeepSpeedConfigModel):
    """Progressive layer drop (reference constants.py PROGRESSIVE_LAYER_DROP;
    runtime/progressive_layer_drop.py:40)."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.1
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    start_step: Optional[int] = None
    end_step: Optional[int] = None
    metric: str = "throughput"
    metric_path: Optional[str] = None
    arg_mappings: Optional[Dict[str, str]] = None
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = True
    model_info: Optional[Dict[str, Any]] = None
    model_info_path: Optional[str] = None
    mp_size: int = 1
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: int = 1024
    min_train_micro_batch_size_per_gpu: int = 1
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50


class DeepSpeedConfig:
    """Parsed + validated config with reference-style attribute surface."""

    def __init__(self, config: Union[str, Dict], mpu=None, mesh_device=None):
        if isinstance(config, str):
            with open(config) as f:
                self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path or dict for the DeepSpeed config, got {type(config)}"
            )
        self.mpu = mpu
        self.mesh_device = mesh_device
        self._initialize_params(self._param_dict)
        self._do_sanity_check()

    def _initialize_params(self, pd: Dict) -> None:
        get = pd.get
        self.train_batch_size = _noauto(get(C.TRAIN_BATCH_SIZE))
        self.train_micro_batch_size_per_gpu = _noauto(get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU))
        self.gradient_accumulation_steps = _noauto(get(C.GRADIENT_ACCUMULATION_STEPS))
        self.steps_per_print = get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.wall_clock_breakdown = get(C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)
        self.gradient_clipping = get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get(
            C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT
        )
        self.sparse_gradients_enabled = get(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.disable_allgather = get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)
        self.seed = get(C.SEED, None)

        self.fp16_config = FP16Config(**get(C.FP16, {}))
        bf16_dict = get(C.BFLOAT16, get(C.BFLOAT16_OLD, {}))
        self.bf16_config = BF16Config(**bf16_dict)
        self.amp_config = AMPConfig(**get(C.AMP, {}))
        self.zero_config = DeepSpeedZeroConfig(**get("zero_optimization", {}))
        self.optimizer_config = OptimizerConfig(**get(C.OPTIMIZER, {})) if get(C.OPTIMIZER) else None
        self.scheduler_config = SchedulerConfig(**get(C.SCHEDULER, {})) if get(C.SCHEDULER) else None
        self.mesh_config = MeshConfig(**get(C.MESH, {}))
        self.compile_config = CompileConfig(**get(C.COMPILE, {}))
        self.analysis_config = AnalysisConfig(**get("analysis", {}))
        self.comms_config = CommsConfig(**{"comms_logger": get(C.COMMS_LOGGER, {})})
        self.activation_checkpointing_config = ActivationCheckpointingConfig(
            **get("activation_checkpointing", {})
        )
        self.flops_profiler_config = FlopsProfilerConfig(**get("flops_profiler", {}))
        self.tracing_config = TracingConfig(**get("tracing", {}))
        # the `monitor` block is canonical (validated whole by pydantic, so
        # a typo'd key fails loudly like every other block); the legacy
        # top-level tensorboard/wandb/csv_monitor keys keep working
        # underneath it, and `csv` aliases `csv_monitor` inside the block
        mon = dict(get("monitor", {}) or {})
        if "csv" in mon:
            mon["csv_monitor"] = mon.pop("csv")
        mon.setdefault("tensorboard", get("tensorboard", {}))
        mon.setdefault("wandb", get("wandb", {}))
        mon.setdefault("csv_monitor", get("csv_monitor", {}))
        self.monitor_config = MonitorConfig(**mon)
        self.checkpoint_config = CheckpointConfig(**get(C.CHECKPOINT, {}))
        self.data_types_config = DataTypesConfig(**get(C.DATA_TYPES, {}))
        self.hybrid_engine = HybridEngineConfig(**get("hybrid_engine", {}))
        self.eigenvalue_config = EigenvalueConfig(**get(C.EIGENVALUE, {}))
        self.pld_config = PLDConfig(**get("progressive_layer_drop", {}))
        self.elasticity_config = ElasticityConfig(**get("elasticity", {}))
        self.autotuning_config = AutotuningConfig(**get("autotuning", {}))
        self.compression_config = pd.get("compression_training", {})
        self.data_efficiency_config = pd.get("data_efficiency", {})
        self.curriculum_learning_config = pd.get("curriculum_learning", {})
        self.nebula_config = pd.get("nebula", {})
        self.aio_config = pd.get("aio", {})

        self.zero_enabled = self.zero_config.stage > ZeroStageEnum.disabled
        self.zero_optimization_stage = int(self.zero_config.stage)
        self.fp16_enabled = self.fp16_config.enabled
        self.bfloat16_enabled = self.bf16_config.enabled
        self.amp_enabled = self.amp_config.enabled
        self.loss_scale = self.fp16_config.loss_scale
        self.initial_dynamic_scale = 2**self.fp16_config.initial_scale_power
        self.dynamic_loss_scale_args = {
            "init_scale": 2**self.fp16_config.initial_scale_power,
            "scale_window": self.fp16_config.loss_scale_window,
            "min_scale": self.fp16_config.min_loss_scale,
            "delayed_shift": self.fp16_config.hysteresis,
            "consecutive_hysteresis": self.fp16_config.consecutive_hysteresis,
        }
        self.checkpoint_tag_validation_enabled = (
            self.checkpoint_config.tag_validation.lower() != "ignore"
        )
        self.checkpoint_tag_validation_fail = self.checkpoint_config.tag_validation.lower() == "fail"
        self.load_universal_checkpoint = self.checkpoint_config.load_universal
        self.elasticity_enabled = self.elasticity_config.enabled

    def resolve_batch_triad(self, dp_world_size: int) -> None:
        """Resolve train_batch = micro_batch × gas × dp (reference config.py).

        Any one or two of the triad may be given; the rest are derived. All
        three given → must multiply out exactly.
        """
        tb, mb, gas = (
            self.train_batch_size,
            self.train_micro_batch_size_per_gpu,
            self.gradient_accumulation_steps,
        )
        if tb and mb and gas:
            if tb != mb * gas * dp_world_size:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} != micro_batch {mb} × gas {gas} × dp {dp_world_size}"
                )
        elif tb and mb:
            gas, rem = divmod(tb, mb * dp_world_size)
            if rem:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by micro_batch {mb} × dp {dp_world_size}"
                )
        elif tb and gas:
            mb, rem = divmod(tb, gas * dp_world_size)
            if rem:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by gas {gas} × dp {dp_world_size}"
                )
        elif mb and gas:
            tb = mb * gas * dp_world_size
        elif mb:
            gas = 1
            tb = mb * dp_world_size
        elif tb:
            mb, rem = divmod(tb, dp_world_size)
            gas = 1
            if rem:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by dp world size {dp_world_size}"
                )
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size / train_micro_batch_size_per_gpu / "
                "gradient_accumulation_steps must be set"
            )
        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas

    def _do_sanity_check(self) -> None:
        if self.fp16_enabled and self.bfloat16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        if self.zero_enabled and self.zero_optimization_stage > int(ZeroStageEnum.max_stage):
            raise DeepSpeedConfigError(
                f"ZeRO stage {self.zero_optimization_stage} > max {int(ZeroStageEnum.max_stage)}"
            )
        if self.optimizer_config and self.optimizer_config.type:
            from deepspeed_tpu.runtime.constants import DEEPSPEED_OPTIMIZERS

            name = self.optimizer_config.type.lower()
            if name not in DEEPSPEED_OPTIMIZERS:
                logger.warning(f"optimizer {name!r} is not a DeepSpeed optimizer; treating as client-style")

    def print_config(self, name: str = "DeepSpeedConfig") -> None:
        logger.info(f"{name}:\n" + json.dumps(self._param_dict, indent=2, cls=ScientificNotationEncoder))


def _noauto(v):
    return None if v == "auto" else v
