"""Model protocol the engine trains.

The reference wraps an eagerly-built ``torch.nn.Module``; a TPU-native engine
trains a *functional* model: ``init`` builds a param pytree, ``apply`` maps
(params, batch) → loss. ``DSModule`` is the protocol; ``wrap_module`` adapts
the things users actually hand to ``deepspeed.initialize`` — a Flax linen
module (+ optional ``loss_fn``), an ``(init_fn, apply_fn)`` pair, or a
``DSModule``.

A key semantic difference, forced by functional autodiff: the loss must be
computed inside the engine's traced step, so the module's ``apply`` (or the
provided ``loss_fn``) returns the scalar loss — the same contract the
reference's ``PipelineModule(loss_fn=...)`` already uses
(``deepspeed/runtime/pipe/module.py``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple


class DSModule:
    """Base class for deepspeed_tpu model families (see ``deepspeed_tpu/models``)."""

    def init(self, rng, batch) -> Any:
        raise NotImplementedError

    def apply(self, params, batch, *, rngs=None, train: bool = True):
        """Return ``loss`` or ``(loss, aux_dict)``."""
        raise NotImplementedError

    def tp_partition_rules(self, params_shapes=None) -> Optional[Any]:
        """Optional pytree of PartitionSpec carrying tensor/model-parallel axes."""
        return None

    def keep_fp32_params(self, params_shapes=None) -> Optional[Any]:
        """Optional pytree of bools marking params that must stay fp32 in the
        compute store under mixed precision (e.g. MoE router weights — the
        reference's TopKGate keeps ``wg`` fp32 for routing stability)."""
        return None


class _FlaxAdapter(DSModule):
    def __init__(self, module, loss_fn: Optional[Callable] = None):
        import inspect

        self.module = module
        self.loss_fn = loss_fn
        # Forward the train flag under whichever name the module's __call__
        # takes ('train' or flax-style 'deterministic'); drop it otherwise.
        self._train_kwarg = None
        try:
            names = set(inspect.signature(type(module).__call__).parameters)
            if "train" in names:
                self._train_kwarg = "train"
            elif "deterministic" in names:
                self._train_kwarg = "deterministic"
        except (TypeError, ValueError):
            pass

    def _inputs(self, batch) -> Tuple[tuple, dict]:
        if isinstance(batch, dict):
            return (), batch
        if isinstance(batch, (tuple, list)):
            return tuple(batch), {}
        return (batch,), {}

    def init(self, rng, batch):
        args, kwargs = self._inputs(batch)
        if self.loss_fn is not None and isinstance(batch, (tuple, list)) and len(batch) == 2:
            # (inputs, labels) convention: the module sees only inputs
            args, kwargs = (batch[0],), {}
        variables = self.module.init(rng, *args, **kwargs)
        return variables

    def apply(self, params, batch, *, rngs=None, train: bool = True):
        args, kwargs = self._inputs(batch)
        labels = None
        if self.loss_fn is not None and isinstance(batch, (tuple, list)) and len(batch) == 2:
            args, kwargs = (batch[0],), {}
            labels = batch[1]
        if self._train_kwarg == "train":
            kwargs["train"] = train
        elif self._train_kwarg == "deterministic":
            kwargs["deterministic"] = not train
        out = self.module.apply(params, *args, **kwargs, rngs=rngs)
        if self.loss_fn is not None:
            return self.loss_fn(out, labels if labels is not None else batch)
        return out


class _FunctionalAdapter(DSModule):
    def __init__(self, init_fn: Callable, apply_fn: Callable, tp_rules: Optional[Callable] = None, loss_fn: Optional[Callable] = None):
        import inspect

        self._init = init_fn
        self._apply = apply_fn
        self._tp_rules = tp_rules
        self.loss_fn = loss_fn
        try:
            sig = inspect.signature(apply_fn)
            names = set(sig.parameters)
            has_varkw = any(p.kind == inspect.Parameter.VAR_KEYWORD for p in sig.parameters.values())
            self._apply_kwargs = has_varkw or {"rngs", "train"} <= names
        except (TypeError, ValueError):
            self._apply_kwargs = False

    def init(self, rng, batch):
        return self._init(rng, batch)

    def apply(self, params, batch, *, rngs=None, train: bool = True):
        if self.loss_fn is not None and isinstance(batch, (tuple, list)) and len(batch) == 2:
            inputs, labels = batch
        else:
            inputs, labels = batch, batch  # loss_fn sees the whole batch as labels
        out = (
            self._apply(params, inputs, rngs=rngs, train=train)
            if self._apply_kwargs
            else self._apply(params, inputs)
        )
        if self.loss_fn is not None:
            return self.loss_fn(out, labels)
        return out

    def tp_partition_rules(self, params_shapes=None):
        if self._tp_rules is None:
            return None
        return self._tp_rules(params_shapes)


def _is_flax_module(model) -> bool:
    try:
        import flax.linen as nn

        return isinstance(model, nn.Module)
    except ImportError:
        return False


def wrap_module(model, loss_fn: Optional[Callable] = None) -> DSModule:
    if isinstance(model, DSModule):
        return model
    if _is_flax_module(model):
        return _FlaxAdapter(model, loss_fn)
    if isinstance(model, (tuple, list)) and len(model) == 2 and all(callable(f) for f in model):
        return _FunctionalAdapter(model[0], model[1], loss_fn=loss_fn)
    # DSModule-protocol object (init(rng, batch) / apply(params, batch, ...))
    # that doesn't inherit the base class
    if hasattr(model, "init") and hasattr(model, "apply"):
        adapter = _FunctionalAdapter(model.init, model.apply, loss_fn=loss_fn)
        if hasattr(model, "tp_partition_rules"):
            adapter.tp_partition_rules = model.tp_partition_rules
        return adapter
    raise TypeError(
        f"Cannot adapt {type(model)} into a trainable module: expected a DSModule, "
        "a Flax module, an (init_fn, apply_fn) pair, or an object with init/apply"
    )
