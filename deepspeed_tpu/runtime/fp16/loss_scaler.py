"""Loss scaling.

Counterpart of ``deepspeed/runtime/fp16/loss_scaler.py`` (``LossScaler``,
``DynamicLossScaler``). The scale lives as a traced fp32 scalar inside the
train-step state so scale updates and overflow-skip happen *inside* jit with
``jnp.where`` — no host round-trip in the hot loop (the reference synchronizes
on the overflow flag every step; we read it back only for logging).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    scale: jax.Array  # fp32 scalar
    good_steps: jax.Array  # int32 scalar
    hysteresis: jax.Array  # int32 scalar


class LossScalerBase:
    """Static (or no-op) scaling."""

    dynamic = False

    def __init__(self, scale: float = 1.0):
        self.init_scale = float(scale)

    def init_state(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.float32(self.init_scale),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis=jnp.zeros((), jnp.int32),
        )

    def update(self, state: LossScaleState, overflow) -> LossScaleState:  # noqa: ARG002
        return state


class LossScaler(LossScalerBase):
    pass


class DynamicLossScaler(LossScalerBase):
    dynamic = True

    def __init__(
        self,
        init_scale: float = 2**32,
        scale_factor: float = 2.0,
        scale_window: int = 1000,
        min_scale: float = 1.0,
        delayed_shift: int = 1,
        consecutive_hysteresis: bool = False,
    ):
        super().__init__(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis

    def init_state(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.float32(self.init_scale),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis=jnp.full((), self.delayed_shift, jnp.int32),
        )

    def update(self, state: LossScaleState, overflow) -> LossScaleState:
        """Pure (jit-traceable) scale update given a bool overflow scalar."""
        hysteresis = jnp.where(overflow, jnp.maximum(state.hysteresis - 1, 0), state.hysteresis)
        must_shrink = overflow & (hysteresis <= 0)
        shrink_scale = jnp.maximum(state.scale / self.scale_factor, self.min_scale)
        window_full = (state.good_steps + 1) >= self.scale_window
        grow_scale = jnp.where(window_full, state.scale * self.scale_factor, state.scale)
        new_scale = jnp.where(must_shrink, shrink_scale, jnp.where(overflow, state.scale, grow_scale))
        new_good = jnp.where(overflow, 0, jnp.where(window_full, 0, state.good_steps + 1))
        new_hyst = jnp.where(
            must_shrink,
            self.delayed_shift,
            jnp.where(
                (~overflow) & (not self.consecutive_hysteresis),
                self.delayed_shift,
                hysteresis,
            ),
        )
        return LossScaleState(scale=new_scale, good_steps=new_good, hysteresis=new_hyst.astype(jnp.int32))


def CreateLossScaler(dtype, static_loss_scale, dynamic_scaling, dynamic_loss_args):
    """Factory mirroring the reference's selection logic (loss_scaler.py)."""
    import jax.numpy as jnp  # noqa: F811

    if dtype == jnp.float16 and dynamic_scaling:
        kwargs = dynamic_loss_args or {}
        return DynamicLossScaler(
            init_scale=kwargs.get(INITIAL_LOSS_SCALE, 2**16),
            scale_window=kwargs.get(SCALE_WINDOW, 1000),
            min_scale=kwargs.get(MIN_LOSS_SCALE, 1.0),
            delayed_shift=kwargs.get(DELAYED_SHIFT, 1),
            consecutive_hysteresis=kwargs.get("consecutive_hysteresis", False),
        )
    scale = static_loss_scale if (dtype == jnp.float16 and static_loss_scale) else 1.0
    return LossScaler(scale=scale)


def has_inf_or_nan(tree) -> jax.Array:
    """Global overflow check (reference ``_has_inf_or_nan`` stage_1_and_2.py:1909)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flags = [~jnp.isfinite(l.astype(jnp.float32)).all() for l in leaves]
    out = jnp.zeros((), jnp.bool_)
    for f in flags:
        out = out | f
    return out
