"""1-bit communication-compressed optimizers
(reference: ``deepspeed/runtime/fp16/onebit/``)."""

from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam
from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLamb
from deepspeed_tpu.runtime.fp16.onebit.zoadam import ZeroOneAdam
