"""0/1 Adam (reference: ``deepspeed/runtime/fp16/onebit/zoadam.py``).

0/1 Adam skips communication AND variance updates on a growing interval
schedule: variance refreshes at ``var_update_scaler``-spaced steps
(doubling policy), momentum syncs likewise (``local_step_scaler``), with
1-bit compression + error feedback on the synced steps. Between syncs each
worker applies its local momentum — here the "local" step degenerates to
the globally-reduced momentum (the engine reduces grads declaratively), so
the schedule controls variance freshness and compression, which is where
the optimizer's convergence behavior lives.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import DSOptimizer


class ZeroOneAdamState(NamedTuple):
    step: Any
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any
    next_var_update: Any  # scalar: next step to refresh variance
    var_interval: Any


class ZeroOneAdam(DSOptimizer):
    def __init__(
        self,
        params=None,  # noqa: ARG002
        deepspeed=None,  # noqa: ARG002
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        var_freeze_step: int = 100000,
        var_update_scaler: int = 16,
        local_step_scaler: int = 32678,  # noqa: ARG002 - parity (see docstring)
        local_step_clipper: int = 16,  # noqa: ARG002
        amsgrad: bool = False,
        cuda_aware: bool = False,  # noqa: ARG002
        comm_backend_name: str = "xla",  # noqa: ARG002
    ):
        if amsgrad:
            raise ValueError("0/1 Adam does not support amsgrad")
        super().__init__(lr=lr, weight_decay=weight_decay, betas=betas, eps=eps)
        self.bias_correction = bias_correction
        self.var_freeze_step = var_freeze_step
        self.var_update_scaler = var_update_scaler

    def init_state(self, params: Any) -> ZeroOneAdamState:
        z = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
        )
        return ZeroOneAdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=z(),
            exp_avg_sq=z(),
            worker_error=z(),
            next_var_update=jnp.ones((), jnp.int32),
            var_interval=jnp.ones((), jnp.int32),
        )

    def state_specs(self, param_specs: Any) -> ZeroOneAdamState:
        from jax.sharding import PartitionSpec

        return ZeroOneAdamState(
            step=PartitionSpec(),
            exp_avg=param_specs,
            exp_avg_sq=param_specs,
            worker_error=param_specs,
            next_var_update=PartitionSpec(),
            var_interval=PartitionSpec(),
        )

    def apply(self, grads, state: ZeroOneAdamState, params, lr) -> Tuple[Any, ZeroOneAdamState]:
        beta1, beta2 = self.defaults["betas"]
        eps = self.defaults["eps"]
        wd = self.defaults["weight_decay"]
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if self.bias_correction else jnp.float32(1.0)
        bc2 = 1.0 - beta2**stepf if self.bias_correction else jnp.float32(1.0)

        update_var = (step >= state.next_var_update) & (step <= self.var_freeze_step)
        # doubling-interval policy (reference's var_update_scaler schedule)
        new_interval = jnp.where(
            update_var,
            jnp.minimum(state.var_interval * 2, jnp.int32(self.var_update_scaler)),
            state.var_interval,
        )
        new_next = jnp.where(update_var, step + new_interval, state.next_var_update)
        frozen = step > self.var_freeze_step

        def leaf(p, g, m, v, err):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m + (1.0 - beta1) * g
            v_cand = beta2 * v + (1.0 - beta2) * g * g
            v_new = jnp.where(update_var & ~frozen, v_cand, v)

            comm = m_new + err
            scale = jnp.mean(jnp.abs(comm))
            m_comp = jnp.sign(comm) * scale
            err_new = jnp.where(frozen, comm - m_comp, jnp.zeros_like(err))
            m_used = jnp.where(frozen, m_comp, m_new)

            update = (m_used / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if wd:
                update = update + wd * p32
            return (p32 - lr * update).astype(p.dtype), m_used, v_new, err_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        cols = [
            treedef.flatten_up_to(t)
            for t in (grads, state.exp_avg, state.exp_avg_sq, state.worker_error)
        ]
        out = [leaf(p, *vals) for p, *vals in zip(flat_p, *cols)]
        unf = lambda i: treedef.unflatten([o[i] for o in out])
        return unf(0), ZeroOneAdamState(
            step=step,
            exp_avg=unf(1),
            exp_avg_sq=unf(2),
            worker_error=unf(3),
            next_var_update=new_next,
            var_interval=new_interval,
        )
