"""1-bit Adam.

Counterpart of the reference's ``OnebitAdam``
(``deepspeed/runtime/fp16/onebit/adam.py``, NCCL/MPI compressed-allreduce
backends ``deepspeed/runtime/comm/{nccl,mpi}.py``). Algorithm (1-bit Adam
paper, and the reference's ``step``):

* **warmup stage** (``freeze_step`` steps): exact Adam; variance ``v``
  adapts.
* **compression stage**: ``v`` is FROZEN; the momentum update is
  communicated as ``sign(m + error) × mean|m + error|`` with an
  error-feedback buffer so compression noise is re-injected next step —
  unbiased in the long run.

TPU mapping: the engine's gradient reduction happens declaratively (GSPMD
psum from shardings), so the sign-compression is applied where it changes
the math — on the momentum actually used for the update — and the wire-level
byte savings are realized by pairing this optimizer with the qgZ
quantized reduce-scatter (``runtime/comm/coalesced_collectives.py``), the
XLA-collective analog of the reference's cupy-packed compressed allreduce.
All compression state (momentum, error) lives in the jitted step.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import DSOptimizer


class OnebitAdamState(NamedTuple):
    step: Any
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any  # error-feedback buffer (reference worker_error)


class OnebitAdam(DSOptimizer):
    def __init__(
        self,
        params=None,  # noqa: ARG002 - torch-API parity
        deepspeed=None,  # noqa: ARG002 - reference signature parity
        lr: float = 1e-3,
        freeze_step: int = 100000,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        eps_inside_sqrt: bool = False,
        weight_decay: float = 0.0,
        max_grad_norm: float = 0.0,
        amsgrad: bool = False,
        cuda_aware: bool = False,  # noqa: ARG002 - parity
        comm_backend_name: str = "xla",  # noqa: ARG002 - parity
    ):
        if amsgrad:
            raise ValueError("1-bit Adam does not support amsgrad")
        if max_grad_norm != 0.0:
            raise ValueError("clip via the engine's gradient_clipping instead")
        super().__init__(lr=lr, weight_decay=weight_decay, betas=betas, eps=eps)
        self.freeze_step = freeze_step
        self.bias_correction = bias_correction
        self.eps_inside_sqrt = eps_inside_sqrt
        # reference exposes these for tests/telemetry
        self.adam_freeze_key = False

    def init_state(self, params: Any) -> OnebitAdamState:
        z = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
        )
        return OnebitAdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=z(),
            exp_avg_sq=z(),
            worker_error=z(),
        )

    def state_specs(self, param_specs: Any) -> OnebitAdamState:
        from jax.sharding import PartitionSpec

        return OnebitAdamState(
            step=PartitionSpec(),
            exp_avg=param_specs,
            exp_avg_sq=param_specs,
            worker_error=param_specs,
        )

    def apply(self, grads: Any, state: OnebitAdamState, params: Any, lr) -> Tuple[Any, OnebitAdamState]:
        beta1, beta2 = self.defaults["betas"]
        eps = self.defaults["eps"]
        wd = self.defaults["weight_decay"]
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        compressed = stepf > float(self.freeze_step)
        bc1 = 1.0 - beta1**stepf if self.bias_correction else jnp.float32(1.0)
        bc2 = 1.0 - beta2**stepf if self.bias_correction else jnp.float32(1.0)

        def leaf(p, g, m, v, err):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m + (1.0 - beta1) * g
            # variance adapts only during warmup (frozen after freeze_step)
            v_new = jnp.where(compressed, v, beta2 * v + (1.0 - beta2) * g * g)

            # compression stage: 1-bit momentum with error feedback
            comm = m_new + err
            scale = jnp.mean(jnp.abs(comm))
            m_comp = jnp.sign(comm) * scale
            err_new = jnp.where(compressed, comm - m_comp, jnp.zeros_like(err))
            m_used = jnp.where(compressed, m_comp, m_new)
            m_kept = jnp.where(compressed, m_comp, m_new)

            if self.eps_inside_sqrt:
                denom = jnp.sqrt(v_new / bc2 + eps)
            else:
                denom = jnp.sqrt(v_new / bc2) + eps
            update = (m_used / bc1) / denom
            if wd:
                update = update + wd * p32
            return (p32 - lr * update).astype(p.dtype), m_kept, v_new, err_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        flat_e = treedef.flatten_up_to(state.worker_error)
        out = [leaf(*t) for t in zip(flat_p, flat_g, flat_m, flat_v, flat_e)]
        unf = lambda i: treedef.unflatten([o[i] for o in out])
        return unf(0), OnebitAdamState(
            step=step, exp_avg=unf(1), exp_avg_sq=unf(2), worker_error=unf(3)
        )
