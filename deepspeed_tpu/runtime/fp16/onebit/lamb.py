"""1-bit LAMB (reference: ``deepspeed/runtime/fp16/onebit/lamb.py``).

LAMB's layerwise trust ratio (‖w‖/‖update‖) composed with 1-bit momentum
compression after ``freeze_step``: during warmup exact LAMB runs and a
running *scaling coefficient* per tensor is recorded; in the compression
stage the frozen variance + recorded coefficients reconstruct the layerwise
scale for the sign-compressed momentum (the reference's
``compensated_momentum`` + ``scaling_coeff`` machinery).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import DSOptimizer


class OnebitLambState(NamedTuple):
    step: Any
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any
    scaling_coeff: Any  # per-leaf scalar recorded during warmup


class OnebitLamb(DSOptimizer):
    def __init__(
        self,
        params=None,  # noqa: ARG002
        deepspeed=None,  # noqa: ARG002
        lr: float = 1e-3,
        freeze_step: int = 100000,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        max_coeff: float = 10.0,
        min_coeff: float = 0.01,
        amsgrad: bool = False,
        cuda_aware: bool = False,  # noqa: ARG002
        comm_backend_name: str = "xla",  # noqa: ARG002
        coeff_beta: float = 0.9,
        factor_max: float = 4.0,  # noqa: ARG002 - parity
        factor_min: float = 0.5,  # noqa: ARG002
        factor_threshold: float = 0.1,  # noqa: ARG002
    ):
        if amsgrad:
            raise ValueError("1-bit LAMB does not support amsgrad")
        super().__init__(lr=lr, weight_decay=weight_decay, betas=betas, eps=eps)
        self.freeze_step = freeze_step
        self.bias_correction = bias_correction
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.coeff_beta = coeff_beta

    def init_state(self, params: Any) -> OnebitLambState:
        z = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
        )
        ones = jax.tree_util.tree_map(lambda p: jnp.ones((), jnp.float32), params)
        return OnebitLambState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=z(),
            exp_avg_sq=z(),
            worker_error=z(),
            scaling_coeff=ones,
        )

    def state_specs(self, param_specs: Any) -> OnebitLambState:
        from jax.sharding import PartitionSpec

        scalar = jax.tree_util.tree_map(
            lambda s: PartitionSpec(),
            param_specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )
        return OnebitLambState(
            step=PartitionSpec(),
            exp_avg=param_specs,
            exp_avg_sq=param_specs,
            worker_error=param_specs,
            scaling_coeff=scalar,
        )

    def apply(self, grads, state: OnebitLambState, params, lr) -> Tuple[Any, OnebitLambState]:
        beta1, beta2 = self.defaults["betas"]
        eps = self.defaults["eps"]
        wd = self.defaults["weight_decay"]
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        compressed = stepf > float(self.freeze_step)
        bc1 = 1.0 - beta1**stepf if self.bias_correction else jnp.float32(1.0)
        bc2 = 1.0 - beta2**stepf if self.bias_correction else jnp.float32(1.0)

        def leaf(p, g, m, v, err, coeff):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m + (1.0 - beta1) * g
            v_new = jnp.where(compressed, v, beta2 * v + (1.0 - beta2) * g * g)

            comm = m_new + err
            scale = jnp.mean(jnp.abs(comm))
            m_comp = jnp.sign(comm) * scale
            err_new = jnp.where(compressed, comm - m_comp, jnp.zeros_like(err))
            m_used = jnp.where(compressed, m_comp, m_new)

            update = (m_used / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if wd:
                update = update + wd * p32
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(update)
            raw = jnp.where(u_norm > 0, w_norm / jnp.maximum(u_norm, 1e-12), 1.0)
            trust = jnp.clip(raw, self.min_coeff, self.max_coeff)
            trust = jnp.where(w_norm > 0, trust, 1.0)
            # warmup records an EMA of the trust ratio; compression freezes it
            coeff_new = jnp.where(
                compressed, coeff, self.coeff_beta * coeff + (1 - self.coeff_beta) * trust
            )
            eff = jnp.where(compressed, coeff, trust)
            return (p32 - lr * eff * update).astype(p.dtype), m_used, v_new, err_new, coeff_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        cols = [
            treedef.flatten_up_to(t)
            for t in (grads, state.exp_avg, state.exp_avg_sq, state.worker_error, state.scaling_coeff)
        ]
        out = [leaf(p, *vals) for p, *vals in zip(flat_p, *cols)]
        unf = lambda i: treedef.unflatten([o[i] for o in out])
        return unf(0), OnebitLambState(
            step=step, exp_avg=unf(1), exp_avg_sq=unf(2), worker_error=unf(3), scaling_coeff=unf(4)
        )
