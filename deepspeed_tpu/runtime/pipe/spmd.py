"""SPMD pipeline executor: the pipe-axis>1 path.

TPU-native replacement for the reference's instruction-interpreting
``PipelineEngine`` (``deepspeed/runtime/pipe/engine.py:54``) and its p2p layer
(``deepspeed/runtime/pipe/p2p.py``): instead of per-process send/recv with a
tensor-meta handshake, the whole pipeline is ONE jitted XLA program —
``shard_map`` manual over the ``pipe`` mesh axis, stage handoffs are
``ppermute`` collectives riding ICI, and the microbatch interleave is a
``lax.scan`` over pipeline ticks. Autodiff through the scan generates the
backward schedule (SendGrad/RecvGrad become the transposed ppermutes), so
forward and backward stay in lockstep with ``schedule.TrainSchedule``'s
ordering without an interpreter.

Structure of one forward (M microbatches, S stages, T = M + S - 1 ticks):

    prefix (embedding &c.)  — computed once on the full batch, replicated
                              over the pipe axis (cheap gather-type work; the
                              same choice GSPMD pipelining makes)
    tick t in [0, T):         stage 0 ingests microbatch t (while t < M);
                              every stage applies its K local layers;
                              outputs ppermute to the next stage
    suffix (head + loss)    — computed on the full collected output,
                              replicated over pipe

Memory: ``lax.scan`` retains each tick's carry (one microbatch activation)
plus per-stage remat'd layer state — the activation footprint of GPipe with
recomputation; the 1F1B live-buffer bound is recovered because XLA schedules
the backward ticks interleaved with forward recomputation.

The stage body requires the pipelined run of layers to be *homogeneous*
(identical param structure and activation shape) — true of the transformer
stacks pipeline parallelism is used for. Heterogeneous prologue/epilogue
layers (embeddings, norms, heads) are detected automatically and run as
prefix/suffix.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.jax_compat import shard_map

from deepspeed_tpu.runtime.module import DSModule
from deepspeed_tpu.utils.logging import log_dist


def _tree_shapes(tree) -> Tuple:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return treedef, tuple((tuple(l.shape), jnp.dtype(l.dtype).name) for l in leaves)


def _shape_of(tree):
    return jax.tree_util.tree_map(lambda l: (tuple(l.shape), jnp.dtype(l.dtype).name), tree)


class PipelineLayout:
    """Prefix / homogeneous-body / suffix split of a layer sequence."""

    def __init__(self, b0: int, b1: int, num_layers: int):
        self.b0 = b0
        self.b1 = b1
        self.num_layers = num_layers

    @property
    def body_len(self) -> int:
        return self.b1 - self.b0


def detect_layout(layers: List[Any], sample_x, rng) -> PipelineLayout:
    """Find the maximal contiguous run of layers with identical parameter
    structure and identical (shape-preserving) activation signature — the
    pipelinable body. Uses abstract evaluation only."""
    sigs = []  # (param_sig, in_sig, out_sig) per layer
    x = sample_x
    for layer in layers:
        p_shape = jax.eval_shape(lambda r, xx, l=layer: l.init(r, xx), rng, x)
        out = jax.eval_shape(lambda pp, xx, l=layer: l.apply(pp, xx, train=True), p_shape, x)
        sigs.append((_tree_shapes(p_shape), _shape_of(x), _shape_of(out)))
        x = out
    best = (0, 0)
    i = 0
    n = len(layers)
    while i < n:
        j = i
        while (
            j < n
            and sigs[j][0] == sigs[i][0]
            and sigs[j][1] == sigs[i][1]
            and sigs[j][2] == sigs[i][1]  # shape-preserving
        ):
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = max(j, i + 1)
    return PipelineLayout(best[0], best[1], n)


class SpmdPipelineModule(DSModule):
    """Wraps a ``PipelineModule`` for execution over a pipe mesh axis > 1.

    Parameters are re-laid-out as::

        {"prefix": [tree, ...],          # replicated over pipe
         "body":   tree with leading [L_body] dim, sharded over pipe,
         "suffix": [tree, ...]}          # replicated over pipe

    and ``apply`` runs the collective-loop pipeline documented in the module
    docstring. ``num_micro`` microbatches are cut from the incoming batch's
    leading dim (so callers pass the full gradient-accumulation batch at
    once — the reference's ``PipelineEngine.train_batch`` contract,
    pipe/engine.py:297).
    """

    def __init__(self, pipeline_module, topology, num_micro: int):
        self.inner = pipeline_module
        self.topology = topology
        self.num_stages = topology.get_pipe_parallel_world_size()
        self.num_micro = max(num_micro, 1)
        self.loss_fn = pipeline_module.loss_fn
        self._layout: Optional[PipelineLayout] = None
        self._layers = None

    # --- layout -----------------------------------------------------------
    def _sample_x(self, batch):
        x = batch
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            x = batch[0]
        elif isinstance(batch, dict):
            x = batch.get("input_ids", batch)
        return jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(np.shape(l), _np_dtype(l)), x
        )

    def _ensure_layout(self, batch):
        if self._layout is not None:
            return
        self._layers = self.inner.build_layers()
        rng = jax.random.PRNGKey(0)
        layout = detect_layout(self._layers, self._sample_x(batch), rng)
        S = self.num_stages
        if layout.body_len < S:
            raise ValueError(
                f"pipeline body of {layout.body_len} homogeneous layers cannot fill "
                f"{S} stages; reduce the pipe axis or add layers"
            )
        if layout.body_len % S != 0:
            # shrink the run from the tail so stages stay balanced
            layout.b1 -= layout.body_len % S
        self._layout = layout
        log_dist(
            f"SpmdPipelineModule: {layout.num_layers} layers → prefix[:{layout.b0}] "
            f"+ body[{layout.b0}:{layout.b1}] over {S} stages "
            f"({layout.body_len // S}/stage) + suffix[{layout.b1}:], "
            f"{self.num_micro} microbatches",
            ranks=[0],
        )

    # --- DSModule surface -------------------------------------------------
    def init(self, rng, batch):
        self._ensure_layout(batch)
        lo = self._layout
        layers = self._layers
        x = self._sample_x(batch)

        prefix_params, body_params, suffix_params = [], [], []
        for i, layer in enumerate(layers):
            rng, sub = jax.random.split(rng)
            p = layer.init(sub, _materialize(x))
            if i < lo.b0:
                prefix_params.append(p)
            elif i < lo.b1:
                body_params.append(p)
            else:
                suffix_params.append(p)
            out = jax.eval_shape(lambda pp, xx, l=layer: l.apply(pp, xx, train=True), p, x)
            x = out
        stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls, axis=0), *body_params)
        return {"prefix": prefix_params, "body": stacked, "suffix": suffix_params}

    def tp_partition_rules(self, params_shapes=None):
        if params_shapes is None:
            return None

        def body_spec(leaf):
            return P("pipe", *([None] * (len(leaf.shape) - 1)))

        def rep(leaf):
            return P(*([None] * len(leaf.shape)))

        return {
            "prefix": jax.tree_util.tree_map(rep, params_shapes["prefix"]),
            "body": jax.tree_util.tree_map(body_spec, params_shapes["body"]),
            "suffix": jax.tree_util.tree_map(rep, params_shapes["suffix"]),
        }

    def apply(self, params, batch, *, rngs=None, train: bool = True):
        self._ensure_layout(batch)
        lo = self._layout
        layers = self._layers
        S = self.num_stages
        M = self.num_micro
        K = lo.body_len // S
        mesh = self.topology.mesh

        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            x, labels = batch
        elif isinstance(batch, dict):
            x, labels = batch.get("input_ids", batch), batch.get("labels")
        else:
            x, labels = batch, None

        # prefix on the full batch (replicated over pipe; per-sample ops so
        # full-batch == per-microbatch evaluation)
        for i in range(lo.b0):
            x = layers[i].apply(params["prefix"][i], x, train=train)

        # anchor the batch dim to the data axes on BOTH sides of the pipe
        # region: without an explicit constraint XLA's propagation picks a
        # different layout for the prefix output than the pipeline body wants
        # and falls back to a full replicate-then-reshard of every microbatch
        # handoff ("[SPMD] Involuntary full rematerialization")
        batch_axes = self.topology.dense_batch_axes()
        from jax.sharding import NamedSharding

        def pin_batch(tree, batch_dim=0):
            if batch_axes is None:
                return tree

            def leaf(l):
                entries = [None] * l.ndim
                entries[batch_dim] = batch_axes
                return jax.lax.with_sharding_constraint(
                    l, NamedSharding(mesh, P(*entries))
                )

            return jax.tree_util.tree_map(leaf, tree)

        x = pin_batch(x)
        B = jax.tree_util.tree_leaves(x)[0].shape[0]
        if B % M != 0:
            raise ValueError(f"batch dim {B} not divisible by {M} microbatches")
        b = B // M
        mbs = jax.tree_util.tree_map(lambda l: l.reshape((M, b) + l.shape[1:]), x)
        mbs = pin_batch(mbs, batch_dim=1)

        # XLA-CPU's AllReducePromotion pass crashes on sub-f32 collectives
        # generated by this region's transposes (cotangent psum / the emits
        # reduce-scatter); promote boundary tensors to f32 on CPU only.
        promote = jax.default_backend() == "cpu"
        act_dtypes = jax.tree_util.tree_map(lambda l: l.dtype, mbs)
        if promote:
            mbs = jax.tree_util.tree_map(lambda l: l.astype(jnp.float32), mbs)

        body_layer = layers[lo.b0]  # homogeneous: one representative

        def stage_fn(stage_params, h):
            """Apply this stage's K layers (scanned over the local stack)."""

            def one_layer(carry, per_layer):
                return body_layer.apply(per_layer, carry, train=train), None

            one_layer = jax.checkpoint(one_layer, prevent_cse=False)
            out, _ = jax.lax.scan(one_layer, h, stage_params)
            return out

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        T = M + S - 1

        def pipeline_body(body_params_local, mbs_in):
            s = jax.lax.axis_index("pipe")

            def tick(carry, t):
                state = carry
                ingest = jax.tree_util.tree_map(
                    lambda m: m[jnp.minimum(t, M - 1)], mbs_in
                )
                inp = jax.tree_util.tree_map(
                    lambda a, c: jnp.where(s == 0, a, c), ingest, state
                )
                if promote:
                    inp = jax.tree_util.tree_map(lambda l, d: l.astype(d), inp, act_dtypes)
                out = stage_fn(body_params_local, inp)
                if promote:
                    out = jax.tree_util.tree_map(lambda l: l.astype(jnp.float32), out)
                nxt = jax.tree_util.tree_map(
                    lambda o: jax.lax.ppermute(o, "pipe", fwd_perm), out
                )
                return nxt, out

            zero_state = jax.tree_util.tree_map(lambda m: jnp.zeros_like(m[0]), mbs_in)
            _, emits = jax.lax.scan(tick, zero_state, jnp.arange(T))
            # ticks [S-1, T) carry the last stage's outputs for microbatches
            # [0, M); all_gather + index broadcasts them off the last stage
            # (bf16-safe, unlike a masked psum which trips XLA-CPU's
            # AllReducePromotion pass)
            outs = jax.tree_util.tree_map(
                lambda e: jax.lax.all_gather(e[S - 1 :], "pipe", axis=0)[S - 1], emits
            )
            return outs

        pipelined = shard_map(
            pipeline_body,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P("pipe"), params["body"]),
                jax.tree_util.tree_map(lambda _: P(), mbs),
            ),
            out_specs=jax.tree_util.tree_map(lambda _: P(), mbs),
            axis_names={"pipe"},
            check_vma=False,
        )
        outs = pipelined(params["body"], mbs)
        if promote:
            outs = jax.tree_util.tree_map(lambda o, d: o.astype(d), outs, act_dtypes)
        outs = pin_batch(outs, batch_dim=1)
        x = jax.tree_util.tree_map(lambda o: o.reshape((B,) + o.shape[2:]), outs)
        x = pin_batch(x)

        # suffix + loss on the full collected output (replicated over pipe)
        for i in range(lo.b1, lo.num_layers):
            x = layers[i].apply(params["suffix"][i - lo.b1], x, train=train)
        if self.loss_fn is not None and labels is not None:
            return self.loss_fn(x, labels)
        return x


def _np_dtype(l):
    d = getattr(l, "dtype", None)
    return np.dtype(d) if d is not None else np.asarray(l).dtype


def _materialize(shape_tree):
    """Zeros matching a ShapeDtypeStruct tree (init needs runnable values)."""
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype) if isinstance(s, jax.ShapeDtypeStruct) else s,
        shape_tree,
    )
