"""Pipeline module: a model described as a list of layer specs.

Counterpart of the reference's ``PipelineModule``
(``deepspeed/runtime/pipe/module.py:86``): users express the network as a
sequence of ``LayerSpec``s; the module partitions the sequence into
``num_stages`` contiguous stages (uniform / parameter-balanced / type-regex,
module.py:368) and tied layers share weights across stages
(``TiedLayerSpec`` :77).

TPU-native semantics: a stage is a *function segment*, not a process — the
pipeline engine shards the layer sequence over the ``pipe`` mesh axis and
microbatches flow between neighbor shards via collective permutes instead of
p2p sends (see ``runtime/pipe/engine.py``).

Each LayerSpec's ``typename`` must be a DSModule-style factory: calling
``typename(*args, **kwargs)`` yields an object with ``init(rng, x)`` and
``apply(params, x, train=...)`` (a Flax module also works — adapted on build).
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.module import DSModule
from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self, log: bool = False):
        if log:
            logger.info(f"Building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self) -> str:
        name = getattr(self.typename, "__name__", str(self.typename))
        return f"LayerSpec({name})"


class TiedLayerSpec(LayerSpec):
    def __init__(self, key: str, typename: Callable, *module_args, forward_fn=None, tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def _count_params(layer) -> int:
    """Best-effort parameter count for balance partitioning."""
    try:
        import jax

        shapes = jax.eval_shape(lambda r: layer.init(r, None), jax.random.PRNGKey(0))
        return sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes))
    except Exception:
        return 1


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Prefix-sum balanced contiguous partition (reference ds_utils.partition_balanced)."""
    n = len(weights)
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + w)
    total = prefix[-1]
    parts = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        # find the index whose prefix is closest to target, monotone
        lo = parts[-1]
        best, best_d = lo, float("inf")
        for i in range(lo, n + 1):
            d = abs(prefix[i] - target)
            if d <= best_d:
                best, best_d = i, d
            else:
                break
        parts.append(best)
    parts.append(n)
    return parts


class PipelineModule(DSModule):
    def __init__(
        self,
        layers: Sequence[LayerSpec],
        num_stages: Optional[int] = None,
        topology=None,
        loss_fn: Optional[Callable] = None,
        seed_layers: bool = False,
        partition_method: str = "parameters",
        activation_checkpoint_interval: int = 0,
        checkpointable_layers=None,  # noqa: ARG002 - API parity
    ):
        self.layer_specs = [l if isinstance(l, LayerSpec) else LayerSpec(lambda m=l: m) for l in layers]
        self.num_stages = num_stages
        self.topology = topology
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self._layers = None
        self._parts: Optional[List[int]] = None

    # --- construction ---------------------------------------------------
    def build_layers(self) -> List[Any]:
        if self._layers is None:
            self._layers = [spec.build() for spec in self.layer_specs]
        return self._layers

    def partition(self, num_stages: int) -> List[int]:
        """Stage boundaries as indices into the layer list."""
        if self._parts is not None and len(self._parts) == num_stages + 1:
            return self._parts
        method = self.partition_method.lower()
        n = len(self.layer_specs)
        if method in ("uniform",):
            self._parts = partition_uniform(n, num_stages)
        elif method in ("parameters",):
            layers = self.build_layers()
            weights = [max(_count_params(l), 1) for l in layers]
            self._parts = partition_balanced(weights, num_stages)
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [
                1 if re.search(pattern, getattr(s.typename, "__name__", ""), re.IGNORECASE) else 0
                for s in self.layer_specs
            ]
            if sum(weights) == 0:
                raise ValueError(f"no layers match partition pattern {pattern!r}")
            self._parts = partition_balanced(weights, num_stages)
        else:
            raise NotImplementedError(f"partition method {self.partition_method!r}")
        return self._parts

    # --- DSModule surface (whole-network; pipeline engine slices stages) --
    def init(self, rng, batch):
        import jax
        import jax.numpy as jnp

        layers = self.build_layers()
        params = []
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            x = batch[0]
        elif isinstance(batch, dict):
            x = batch.get("input_ids", batch)
        else:
            x = batch
        for layer in layers:
            rng, sub = jax.random.split(rng)
            p = layer.init(sub, x)
            params.append(p)
            # thread the next layer's input as zeros of the right shape — a
            # ShapeDtypeStruct is not a runnable value, so materialize it
            out_shape = jax.eval_shape(lambda pp, xx, l=layer: l.apply(pp, xx, train=True), p, x)
            x = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), out_shape)
        return params

    def apply(self, params, batch, *, rngs=None, train: bool = True):
        layers = self.build_layers()
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            x, labels = batch
        elif isinstance(batch, dict):
            x, labels = batch.get("input_ids", batch), batch.get("labels")
        else:
            x, labels = batch, None
        for p, layer in zip(params, layers):
            x = layer.apply(p, x, train=train)
        if self.loss_fn is not None and labels is not None:
            return self.loss_fn(x, labels)
        return x
