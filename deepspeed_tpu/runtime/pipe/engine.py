"""Pipeline engine.

Counterpart of the reference's ``PipelineEngine``
(``deepspeed/runtime/pipe/engine.py:54``). The reference interprets an
instruction schedule (``schedule.py``) with p2p sends between stage
processes; here the pipe axis is a mesh dimension and the whole schedule is
one jitted collective loop (``runtime/pipe/spmd.py``) — see that module for
the mapping. ``train_batch``/``eval_batch`` (reference :297/:404) are the
blessed API: one call consumes ``gradient_accumulation_steps`` microbatches
and takes one optimizer step, exactly the reference contract. Direct
``backward``/``step`` calls raise, mirroring the reference
(pipe/engine.py:1290-1305 disables them).

With pipe axis == 1 the layer sequence runs as one fused XLA program and the
engine behaves like the dense engine with train_batch sugar.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.spmd import SpmdPipelineModule
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):
    _is_pipe_engine = True
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.num_stages = self.topology.get_pipe_parallel_world_size()
        self.micro_batches = self.gradient_accumulation_steps()
        self._pipe_parallel = self.num_stages > 1
        self.batch_fn = None
        if self._pipe_parallel:
            # all microbatches flow through ONE fwd_bwd whose loss is already
            # the microbatch mean → no further division by gas at step time
            self._gas_divisor = 1
            self.module = SpmdPipelineModule(
                self.module, self.topology, num_micro=self.micro_batches
            )
        log_dist(
            f"PipelineEngine: {len(self.module.inner.layer_specs) if self._pipe_parallel else len(self.module.layer_specs)} "
            f"layers over {self.num_stages} stage(s), {self.micro_batches} microbatches/step",
            ranks=[0],
        )

    # --- reference API: train_batch/eval_batch --------------------------
    def train_batch(self, data_iter=None, batch=None):
        """One full step: gas microbatches + optimizer step
        (reference pipe/engine.py:297)."""
        self.train()
        if not self._pipe_parallel:
            combined = self._collect_batch(data_iter, batch)
            return super().train_batch(batch=combined)
        combined = self._collect_batch(data_iter, batch)
        loss = super().forward(combined)
        self._in_forward = False
        # one fused fwd_bwd covered all gas microbatches: advance the
        # micro-step counter and sample count to the GAS boundary, then take
        # the model step (step() accounts the final microbatch itself)
        self.micro_steps += self.micro_batches - 1
        self.global_samples += (
            self.train_micro_batch_size_per_gpu()
            * self.data_parallel_world_size()
            * (self.micro_batches - 1)
        )
        self.step()
        return jax.device_get(loss)

    def eval_batch(self, data_iter=None, batch=None, return_logits: bool = False):  # noqa: ARG002
        """Evaluate over a full step's worth of microbatches — consumes
        ``micro_batches`` items from ``data_iter`` at ANY pipe size (the
        reference contract, pipe/engine.py:404)."""
        self.eval()
        combined = self._collect_batch(data_iter, batch)
        out = super().forward(combined)
        self.train()
        return out

    def _collect_batch(self, data_iter, batch):
        """Concatenate gas microbatches into the full-step batch the spmd
        pipeline slices internally (reference loads per-instruction,
        pipe/engine.py:770). Applies ``batch_fn`` when set."""
        if batch is not None:
            combined = batch  # caller already passed the full-step batch
        else:
            parts = [next(data_iter) for _ in range(self.micro_batches)]
            if self.batch_fn is not None:
                parts = [self.batch_fn(p) for p in parts]
            combined = (
                parts[0]
                if len(parts) == 1
                else jax.tree_util.tree_map(lambda *ls: jnp.concatenate(ls, axis=0), *parts)
            )
        if batch is not None and self.batch_fn is not None:
            combined = self.batch_fn(combined)
        return combined

    # --- disabled surfaces (reference pipe/engine.py:1290-1305) ----------
    def forward(self, batch):
        if self._pipe_parallel:
            raise RuntimeError(
                "PipelineEngine does not support forward(); use train_batch/eval_batch"
            )
        return super().forward(batch)

    def backward(self, loss, **kwargs):
        if self._pipe_parallel:
            raise RuntimeError(
                "PipelineEngine does not support backward(); use train_batch"
            )
        return super().backward(loss, **kwargs)

    def set_dataloader(self, loader) -> None:
        self.training_dataloader = loader

    def set_batch_fn(self, fn) -> None:
        self.batch_fn = fn

    def is_first_stage(self) -> bool:
        """SPMD: every process spans all stages (stage = mesh coordinate)."""
        return True

    def is_last_stage(self) -> bool:
        return True
