"""Pipeline engine.

Counterpart of the reference's ``PipelineEngine``
(``deepspeed/runtime/pipe/engine.py:54``) and its instruction schedule
(``deepspeed/runtime/pipe/schedule.py``). Round-1 scope: the engine accepts a
``PipelineModule`` and trains it with the standard fused step — on TPU a
1-stage pipeline (pipe mesh axis = 1) is exactly the dense engine, and the
layer sequence runs as one XLA program. ``train_batch``/``eval_batch``
(reference :297/:404) are provided so user loops port unchanged.

The pipe-axis>1 path (microbatch interleave via ``shard_map`` over the
``pipe`` axis with ``ppermute`` stage handoffs — the 1F1B schedule expressed
as a ``lax.scan`` over microbatches) is staged in
``deepspeed_tpu/runtime/pipe/schedule.py`` and wired up when the pipe axis is
enabled; until then a pipe axis > 1 raises rather than silently misplacing
layers.
"""

from __future__ import annotations

import jax

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.topology.get_pipe_parallel_world_size() > 1:
            raise NotImplementedError(
                "pipe mesh axis > 1: the scan/ppermute 1F1B schedule is not wired up yet; "
                "run with mesh.pipe=1 (layers execute as one fused XLA program)"
            )
        self.micro_batches = self.gradient_accumulation_steps()
        log_dist(
            f"PipelineEngine: {len(self.module.layer_specs)} layers, "
            f"{self.micro_batches} microbatches/step",
            ranks=[0],
        )

    def train_batch(self, data_iter=None, batch=None):
        """Full pipeline step: gas microbatches + optimizer step
        (reference pipe/engine.py:297)."""
        self.train()
        return super().train_batch(data_iter=data_iter, batch=batch)

    def eval_batch(self, data_iter=None, batch=None, return_logits: bool = False):  # noqa: ARG002
        self.eval()
        b = next(data_iter) if batch is None else batch
        out = self.forward(b)
        self.train()
        return out

    def set_dataloader(self, loader) -> None:
        self.training_dataloader = loader

    def is_first_stage(self) -> bool:
        return True

    def is_last_stage(self) -> bool:
        return True
