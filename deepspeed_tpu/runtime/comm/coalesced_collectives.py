"""ZeRO++ quantized / coalesced collectives.

Counterpart of the reference's ``deepspeed/runtime/comm/coalesced_collectives.py``:
``all_to_all_quant_reduce`` (:31 — 4-bit intra-node all-to-all then
inter-node reduce) and ``reduce_scatter_coalesced`` (:87). On TPU the
collectives are expressed inside ``shard_map`` so the quantization happens
*before* bytes hit the ICI:

* ``reduce_scatter_coalesced``  — stacked tensors, one fused psum_scatter;
* ``quantized_reduce_scatter``  — int8 block-quantized all-to-all + local
  reduction (qgZ): each chip sends only its peers' int8 shards + scales,
  cutting gradient-sync bandwidth 4× vs fp32 / 2× vs bf16;
* ``quantized_all_gather``      — int8 weight gather (qwZ) for ZeRO-3
  param gathers.

Both quantized ops are error-free in exact arithmetic only for the scales'
dynamic range — like the reference, they trade a small quantization error
for bandwidth; tests bound the error against the exact collective.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.jax_compat import shard_map

from deepspeed_tpu.ops.quantizer import dequantize, quantize


def row_coalesced_layout(
    shapes: Sequence[Sequence[int]], world: int
) -> List[Tuple[int, int]]:
    """Column layout of the ``[world, C]`` coalesced buffer: for each input
    (whose dim 0 is the world-divisible shard dim), ``(col_offset, width)``.
    Row k of the buffer holds every input's k-th shard chunk back-to-back,
    so a single dim-0 collective on the buffer lands each input directly in
    its own per-leaf scattered layout — no inter-device reshard afterwards.
    Shared by the overlap plan's bucketed grad reduce-scatter
    (``runtime/zero/overlap.py``) and the coalesced collectives below."""
    layout = []
    off = 0
    for shape in shapes:
        n = int(np.prod(shape)) if len(shape) else 1
        width = -(-n // world)  # ceil: non-divisible inputs pad to a full chunk
        layout.append((off, width))
        off += width
    return layout


def pack_row_coalesced(tensors: Sequence[jnp.ndarray], world: int) -> jnp.ndarray:
    """Concatenate tensors (shard dim leading) into one ``[world, C]``
    buffer per :func:`row_coalesced_layout`. Pure data movement."""
    cols = []
    for t in tensors:
        flat = t.reshape(-1)
        pad = (-flat.shape[0]) % world
        if pad:
            flat = jnp.pad(flat, (0, pad))
        cols.append(flat.reshape(world, -1))
    return jnp.concatenate(cols, axis=1)


def unpack_row_coalesced(
    buf: jnp.ndarray, shapes: Sequence[Sequence[int]], world: int
) -> List[jnp.ndarray]:
    """Inverse of :func:`pack_row_coalesced`: split the ``[world, C]``
    buffer back into tensors of ``shapes`` (shard dim leading)."""
    layout = row_coalesced_layout(shapes, world)
    out = []
    for shape, (off, width) in zip(shapes, layout):
        n = int(np.prod(shape)) if len(shape) else 1
        flat = buf[:, off : off + width].reshape(-1)[:n]
        out.append(flat.reshape(tuple(shape)))
    return out


def reduce_scatter_coalesced(
    tensors: Sequence[jnp.ndarray], mesh: Mesh, axis_name: str = "data"
) -> List[jnp.ndarray]:
    """Reduce-scatter a list of tensors in ONE collective (reference :87):
    flatten + concat, single psum_scatter over the axis, split back. Each
    returned tensor is the caller's 1/world shard of the sum."""
    world = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    flats = [t.reshape(-1) for t in tensors]
    sizes = [f.shape[0] for f in flats]
    padded = [
        jnp.pad(f, (0, (-f.shape[0]) % world)) for f in flats
    ]
    buf = jnp.concatenate(padded)

    def body(x):
        # x: this chip's full contribution; each chip keeps its reduced shard
        return jax.lax.psum_scatter(x, axis_name, tiled=True)

    out = shard_map(
        body, mesh=mesh, in_specs=P(), out_specs=P(axis_name), check_vma=False
    )(buf)
    # out is the global scattered array; split per input
    shards = []
    off = 0
    for f, size in zip(padded, sizes):
        n = f.shape[0]
        shards.append(out[off : off + n][: size])
        off += n
    return shards


def quant_a2a_reduce_local(
    flat: jnp.ndarray, axis_name: str, world: int, gpg: int, num_bits: int
) -> jnp.ndarray:
    """Inside ``shard_map``: quantize this chip's contribution per destination
    chunk, all-to-all the int8 payload + scales, dequantize and sum — the qgZ
    wire pattern shared by ``quantized_reduce_scatter`` and the ZeRO++ grad
    path. ``flat`` [n] with n divisible by world×gpg; returns this chip's
    summed chunk [n/world] in fp32."""
    n = flat.shape[0]
    q, scale = quantize(flat.reshape(world, n // world), world * gpg, num_bits)
    q = q.reshape(world, gpg, -1)
    scale = scale.reshape(world, gpg)
    q_recv = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_recv = jax.lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0, tiled=False)
    deq = q_recv.astype(jnp.float32) * s_recv[..., None]
    return jnp.sum(deq, axis=0).reshape(-1)


def quant_all_gather_local(
    x: jnp.ndarray, axis_name: str, num_groups: int, num_bits: int
) -> jnp.ndarray:
    """Inside ``shard_map``: quantize the local array, all-gather int8 +
    scales, dequantize — the qwZ wire pattern shared by
    ``quantized_all_gather`` and the ZeRO++ param gathers. Returns
    [world, x.size] fp32 (one dequantized row per source chip)."""
    q, scale = quantize(x, num_groups, num_bits)
    qg = jax.lax.all_gather(q, axis_name, axis=0, tiled=False)
    sg = jax.lax.all_gather(scale, axis_name, axis=0, tiled=False)
    world = qg.shape[0]
    deq = qg.astype(jnp.float32) * sg[..., None]
    return deq.reshape(world, x.size)


def quantized_reduce_scatter(
    tensor: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "data",
    num_bits: int = 8,
    groups_per_shard: int = 16,
) -> jnp.ndarray:
    """qgZ (reference ``all_to_all_quant_reduce``): each chip quantizes its
    contribution per destination shard, all-to-alls the int8 payload +
    scales, and reduces the dequantized shards locally. Returns the global
    array whose shard s holds sum_over_chips(chunk_s)."""
    world = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    flat = tensor.reshape(-1)
    pad = (-flat.shape[0]) % (world * groups_per_shard)
    flat = jnp.pad(flat, (0, pad))
    n = flat.shape[0]

    def body(x):
        # x: this chip's full local copy [n] (replicated input)
        return quant_a2a_reduce_local(
            x, axis_name, world, groups_per_shard, num_bits
        ).reshape(1, n // world)

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=P(),
        out_specs=P(axis_name),
        check_vma=False,
    )(flat)
    return out.reshape(-1)[: tensor.size + pad][: tensor.size] if pad else out.reshape(-1)


def quantized_all_gather(
    shard: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "data",
    num_bits: int = 8,
    num_groups: int = 16,
) -> jnp.ndarray:
    """qwZ (reference partition_parameters.py:654 quantized all-gather):
    each chip quantizes its local shard, gathers int8 + scales, dequantizes.
    ``shard`` is a global array sharded over ``axis_name`` dim 0."""

    def body(x):
        # x: local shard
        return quant_all_gather_local(x, axis_name, num_groups, num_bits).reshape(-1)

    local_shape = (shard.shape[0],) + shard.shape[1:]
    out = shard_map(
        body, mesh=mesh, in_specs=P(axis_name), out_specs=P(), check_vma=False
    )(shard.reshape(shard.shape[0], -1))
    return out.reshape((-1,) + shard.shape[1:])


def all_to_all_quant_reduce(tensors, mesh: Mesh, axis_name: str = "data", **kw):
    """Reference-named entry (``coalesced_collectives.py:31``): quantized
    grad reduce over a tensor list; each result is the caller's summed
    shard."""
    return [quantized_reduce_scatter(t, mesh, axis_name, **kw) for t in tensors]
