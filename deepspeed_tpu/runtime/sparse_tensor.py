"""Sparse gradient support for embedding tables.

Reference capability (``deepspeed/runtime/sparse_tensor.py:68`` +
``engine.py:2398-2465``): embedding gradients are converted to a
(values, indices) ``SparseTensor`` and the DP reduction all-gathers the
compact pairs instead of all-reducing the dense [vocab, hidden] table — a
bandwidth win whenever the batch touches far fewer rows than the table has.

TPU-native mechanism: the same math as a *declarative collective choice*.
``sparse_embedding_lookup`` is the plain gather on the forward; its custom
VJP computes the table cotangent inside a ``shard_map`` over the data axes —
each shard all-gathers every shard's (token-ids, row-cotangents) pairs (the
compact representation; wire bytes ≈ global_tokens × (hidden+1) × 4) and
scatter-adds them locally into one [vocab, hidden] buffer. The result is
bit-identical to the dense path's psum of per-shard scatter-adds, but the
interconnect never carries the dense table. ``SparseTensor`` itself is kept
as the host-side surface for parity with the reference API.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.jax_compat import shard_map


class SparseTensor:
    """Compact (indices, values) view of a row-sparse dense tensor
    (reference ``runtime/sparse_tensor.py:68``)."""

    def __init__(self, indices, values, dense_size: Tuple[int, ...]):
        self.indices = jnp.asarray(indices)
        self.values = jnp.asarray(values)
        self.dense_size = tuple(dense_size)

    @staticmethod
    def from_dense(tensor, indices=None) -> "SparseTensor":
        t = jnp.asarray(tensor)
        if indices is None:
            row_mass = jnp.abs(t).sum(axis=tuple(range(1, t.ndim)))
            indices = jnp.nonzero(row_mass)[0]
        return SparseTensor(indices, t[indices], t.shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self) -> int:
        return int(self.indices.size + self.values.size)


def _scatter_rows(tokens, g_rows, vocab: int, dtype):
    """Σ over token occurrences: dense [vocab, H] from compact pairs."""
    H = g_rows.shape[-1]
    flat_tok = tokens.reshape(-1)
    flat_g = g_rows.reshape(-1, H).astype(jnp.float32)
    out = jnp.zeros((vocab, H), jnp.float32)
    return out.at[flat_tok].add(flat_g).astype(dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def sparse_embedding_lookup(table, tokens, data_axes: Optional[Tuple[str, ...]] = None):
    """``table[tokens]`` whose backward reduces over DP as compact pairs.

    ``data_axes``: mesh axis names the batch's leading dim is sharded over
    (``Topology.dense_batch_axes()``); None/empty → single-shard scatter-add
    (no collective at all).
    """
    return table[tokens]


def _sel_fwd(table, tokens, data_axes):
    # the table itself rides the residuals only for its STATIC aval
    # (shape/dtype); its data is unused in bwd and DCE'd by XLA
    return table[tokens], (table, tokens)


def _sel_bwd(data_axes, res, g):
    table, tokens = res
    (vocab, hidden), dtype = table.shape, table.dtype
    axes: Tuple[str, ...] = tuple(data_axes) if data_axes else ()
    if axes:
        from deepspeed_tpu.parallel.mesh import get_topology

        topo = get_topology()
        axes = tuple(a for a in axes if topo.axis_size(a) > 1)
    if not axes:
        return _scatter_rows(tokens, g, vocab, dtype), None

    mesh = topo.mesh

    def inner(tok_shard, g_shard):
        # the compact pairs are what crosses the interconnect
        toks_all = jax.lax.all_gather(tok_shard, axes, axis=0, tiled=True)
        g_all = jax.lax.all_gather(g_shard, axes, axis=0, tiled=True)
        return _scatter_rows(toks_all, g_all, vocab, dtype)

    batch_spec = axes if len(axes) > 1 else axes[0]
    d_table = shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(batch_spec, None), P(batch_spec, None, None)),
        out_specs=P(),
        check_vma=False,
    )(tokens, g)
    return d_table, None


sparse_embedding_lookup.defvjp(_sel_fwd, _sel_bwd)
