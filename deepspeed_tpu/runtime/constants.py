"""Config keys and defaults (reference: ``deepspeed/runtime/constants.py``)."""

#############################################
# Batch size triad
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
CPU_ADAM_OPTIMIZER = "deepspeedcpuadam"
CPU_ADAGRAD_OPTIMIZER = "deepspeedcpuadagrad"
ADAGRAD_OPTIMIZER = "adagrad"
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB_OPTIMIZER = "fusedlamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
SGD_OPTIMIZER = "sgd"
LION_OPTIMIZER = "lion"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER,
    ADAMW_OPTIMIZER,
    FUSED_ADAM_OPTIMIZER,
    CPU_ADAM_OPTIMIZER,
    CPU_ADAGRAD_OPTIMIZER,
    ADAGRAD_OPTIMIZER,
    LAMB_OPTIMIZER,
    FUSED_LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER,
    SGD_OPTIMIZER,
    LION_OPTIMIZER,
]

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False
FP16_AUTO_CAST = "auto_cast"
FP16_AUTO_CAST_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

#############################################
# Logging / misc
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
USE_NODE_LOCAL_STORAGE_CHECKPOINT_DEFAULT = False

#############################################
# Mesh / parallelism (TPU-native section)
#############################################
MESH = "mesh"

#############################################
# Communication
#############################################
COMMS_LOGGER = "comms_logger"
SEED = "seed"

#############################################
# Routing
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Gradient-accumulation dtype
#############################################
DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

#############################################
# Compile controls (TPU-native section)
#############################################
COMPILE = "compile"
FUSE_GRAD_ACCUM = "fuse_grad_accum"
FUSE_GRAD_ACCUM_DEFAULT = False
COMPILE_CACHE_DIR = "cache_dir"
COMPILE_CACHE_DIR_DEFAULT = None

#############################################
# Eigenvalue (MoQ)
#############################################
EIGENVALUE = "eigenvalue"

# Pipeline config keys
PIPE_REPLICATED = "ds_pipe_replicated"
