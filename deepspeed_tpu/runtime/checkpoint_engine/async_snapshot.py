"""Async atomic checkpointing: snapshot device→host, persist in background.

The reference's Nebula engine
(``runtime/checkpoint_engine/nebula_checkpoint_engine.py``) hides checkpoint
persistence behind training compute by snapshotting to host memory and
writing from a service thread. This is that design realized TPU-natively,
with the same hiding discipline as the PR-5 prefetch pipeline:

* ``host_snapshot`` enqueues **every leaf's D2H copy first**
  (``copy_to_host_async``) and only then materializes them — the transfers
  overlap each other instead of serializing one ``device_get`` at a time.
  This is the ONLY on-step cost (the ``ckpt_stall_ms`` the bench records):
  it must complete before returning because the step programs donate the
  state tuple, so the next dispatch would invalidate the source buffers.
* the snapshot is handed to a background writer thread that runs the staged
  atomic save (``orbax_checkpoint_engine.py``), the commit rename, and the
  ``latest`` marker update — disk latency never blocks the step loop.
* **double-buffered**: up to ``max_inflight`` snapshots may be queued; a
  save beyond that waits for the oldest write to drain (bounding host RAM
  at ``max_inflight`` state copies). No jitted program is involved anywhere
  — compile/dispatch telemetry shows zero new programs on the hot path.

Crash semantics: the writer thread catches ``Exception`` (surfaced at the
next ``submit``/``wait`` fence) but NOT ``BaseException`` — a chaos
``ChaosKilled`` kills the thread mid-write exactly like a real ``kill -9``,
leaving staged-but-uncommitted garbage that the atomic layout is designed to
survive.
"""

from __future__ import annotations

import atexit
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.atomic import write_latest_marker
from deepspeed_tpu.utils.logging import logger

# Exit-drain plumbing. A clean interpreter exit must flush every queued
# snapshot, and the WHERE is delicate: the writer persists through orbax,
# which schedules work on concurrent.futures executors that are shut down
# by a threading._register_atexit callback (runs in REVERSE registration
# order, before non-daemon threads are joined, before classic atexit). So
# the drain is registered via the same hook, LAZILY at first writer
# creation — later registration = earlier execution, i.e. before the
# executors close. Classic atexit is far too late (new threads cannot
# start during finalization; an orbax join there hangs forever).
_LIVE_WRITERS: "weakref.WeakSet" = weakref.WeakSet()
_DRAIN_REGISTERED = False


def _drain_live_writers() -> None:
    for writer in list(_LIVE_WRITERS):
        try:
            writer.wait()
        except Exception as e:
            logger.error(f"checkpoint writer drain at exit failed: {e}")


def _register_exit_drain() -> None:
    global _DRAIN_REGISTERED
    if _DRAIN_REGISTERED:
        return
    _DRAIN_REGISTERED = True
    register = getattr(threading, "_register_atexit", None)
    if register is not None:  # CPython 3.9+
        register(_drain_live_writers)
    else:  # best effort; the non-daemon worker is the real backstop here
        atexit.register(_drain_live_writers)


def host_snapshot(tree: Any) -> Any:
    """Materialize a state pytree on the host. All D2H copies are enqueued
    before any is awaited, so the transfers pipeline; non-array leaves
    (counters, config dicts) pass through untouched. Returns a tree of
    numpy arrays + plain python values, safe to hand to another thread
    while the donating step programs keep running."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except Exception:
                pass  # older jax / committed host arrays: device_get below
    host = [
        np.asarray(jax.device_get(leaf)) if isinstance(leaf, jax.Array) else leaf
        for leaf in leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, host)


def tree_fully_addressable(tree: Any) -> bool:
    """True when every jax leaf is locally materializable — the async path's
    precondition (a cross-process global array has no single-host copy; its
    save must go through the collective orbax path synchronously)."""
    return all(
        leaf.is_fully_addressable
        for leaf in jax.tree_util.tree_leaves(tree)
        if isinstance(leaf, jax.Array)
    )


@dataclass
class _Job:
    state: Any
    path: str
    tag: str
    save_dir: Optional[str]  # None = skip the latest-marker update
    done: threading.Event = field(default_factory=threading.Event)


class AsyncCheckpointWriter:
    """Background persister over a (staged, atomic) checkpoint engine."""

    def __init__(self, inner, max_inflight: int = 2, tracer=None):
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        self.inner = inner
        self.max_inflight = int(max_inflight)
        # unified-tracing hookup: the writer thread records ckpt.stage /
        # ckpt.commit spans onto the ENGINE's tracer — the tracer's ring
        # buffer and nesting state are thread-safe by contract (the tracer
        # test suite exercises exactly this writer)
        from deepspeed_tpu.profiling.tracer import NULL_TRACER

        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._jobs: deque = deque()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[Exception] = None
        self.saves = 0
        self.last_save_s = 0.0
        _LIVE_WRITERS.add(self)
        _register_exit_drain()

    # --- public surface -------------------------------------------------
    def submit(self, host_state: Any, path: str, tag: str, save_dir: Optional[str]) -> None:
        """Queue one snapshot for persistence. Blocks only while
        ``max_inflight`` older writes are still draining."""
        self._raise_pending_error()
        job = _Job(state=host_state, path=path, tag=tag, save_dir=save_dir)
        while True:
            with self._lock:
                self._reap_locked()
                if self._thread is not None and not self._thread.is_alive() and self._jobs:
                    # the writer died mid-queue (a chaos kill): the remaining
                    # jobs will never drain — drop them so the caller is not
                    # wedged behind a dead thread
                    self._jobs.clear()
                if len(self._jobs) < self.max_inflight:
                    self._jobs.append(job)
                    self._ensure_worker_locked()
                    return
                oldest = self._jobs[0]
            oldest.done.wait(timeout=0.05)

    def wait(self) -> None:
        """Fence: block until every queued write has committed (or the
        writer died), then surface any persist error."""
        while True:
            with self._lock:
                self._reap_locked()
                if not self._jobs:
                    break
                job = self._jobs[0]
                dead = self._thread is None or not self._thread.is_alive()
            if dead:
                with self._lock:
                    self._jobs.clear()
                break
            job.done.wait(timeout=0.05)
        self._raise_pending_error()

    def pending(self) -> int:
        with self._lock:
            self._reap_locked()
            return len(self._jobs)

    # --- internals -------------------------------------------------------
    def _raise_pending_error(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"async checkpoint persist failed: {err}") from err

    def _reap_locked(self) -> None:
        while self._jobs and self._jobs[0].done.is_set():
            self._jobs.popleft()

    def _ensure_worker_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            # NON-daemon and alive only while the queue is non-empty: even
            # without the _register_atexit drain, threading._shutdown's
            # non-daemon join waits out an in-flight write. Abrupt deaths
            # are untouched — SIGKILL/os._exit skip every join.
            self._thread = threading.Thread(
                target=self._run, name="ckpt-writer", daemon=False
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                self._reap_locked()
                if not self._jobs:
                    # drained: exit; submit() restarts the worker on demand
                    self._thread = None
                    return
                job = self._jobs[0]
            try:
                t0 = time.perf_counter()
                with self.tracer.span("ckpt.stage", tag=job.tag):
                    self.inner.save(job.state, job.path)
                with self.tracer.span("ckpt.commit", tag=job.tag):
                    self.inner.commit(job.tag)
                    if job.save_dir is not None:
                        write_latest_marker(job.save_dir, job.tag)
                self.last_save_s = time.perf_counter() - t0
                self.saves += 1
            except Exception as e:  # surfaced at the next fence
                self._error = e
                logger.error(f"async checkpoint persist failed: {e}")
            except BaseException:
                # a chaos/interpreter kill mid-write: THIS write dies like
                # the process would — torn staged state stays on disk, no
                # error is recorded. Queued later snapshots are independent
                # saves, so a replacement worker picks them up (only the
                # killed write is lost, matching a single torn save).
                job.done.set()
                with self._lock:
                    self._thread = None
                    self._reap_locked()
                    if self._jobs:
                        self._ensure_worker_locked()
                return
            job.done.set()
