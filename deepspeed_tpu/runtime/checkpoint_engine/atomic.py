"""Atomic persistence primitives for checkpoints and markers.

The failure model is ``kill -9`` at ANY instant (TPU preemption). The only
durable commit primitive POSIX gives us is ``rename`` within a filesystem,
so every checkpoint artifact follows the same discipline:

* **files** — write to a ``.tmp-<pid>`` sibling, flush + ``fsync``, rename
  over the final name, ``fsync`` the parent directory (the rename itself is
  not durable until the directory entry is);
* **checkpoint directories** — the engine stages the WHOLE checkpoint under
  ``<final>.staging-<pid>``, writes a ``_COMPLETE`` sentinel last, and
  ``commit`` renames the directory into place. A crash before the rename
  leaves only staging garbage (ignored, reclaimed on the next save of the
  same tag); a crash after it leaves a fully valid checkpoint;
* **the ``latest`` marker** — updated atomically AND only after commit, so
  it can never name a torn checkpoint. A crash between commit and the
  marker update leaves ``latest`` on the previous checkpoint, which is why
  ``find_latest_valid`` (the ``auto_resume`` discovery path) scans and
  validates rather than trusting the marker blindly.

Chaos injection points (``utils/chaos.py``): ``ckpt.pre_commit`` right
before the commit rename, ``ckpt.post_commit`` right after it.
"""

from __future__ import annotations

import os
import re
import shutil
import threading
from typing import List, Optional, Tuple

from deepspeed_tpu.utils import chaos

COMPLETE_MARKER = "_COMPLETE"
LATEST_NAME = "latest"

_STEP_TAG = re.compile(r"(\d+)\s*$")
_TRASH_NAME = re.compile(r"^(.+)\.trash-\d+$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory is torn or unreadable (missing metadata,
    missing array payload, undecodable pickle). Raised instead of letting a
    ``FileNotFoundError``/``UnpicklingError`` surface from deep inside the
    storage layer; ``auto_resume`` treats it as 'skip this tag'."""


class CheckpointLoadError(RuntimeError):
    """A readable checkpoint does not fit the current run: a module leaf's
    shape/dtype disagrees with the live state, the trees differ, or the
    mesh topology changed. Raised with the offending leaf and both shapes
    named — instead of the cryptic tree-unflatten/reshape failure the raw
    adoption would hit later."""


def fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (renames/creates) under
    ``path``. Best-effort on filesystems without dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(
    path: str, data: bytes, do_fsync: bool = True, reclaim_stale: bool = False
) -> None:
    """Write-to-temp -> fsync -> rename: ``path`` either holds its previous
    content or all of ``data``, never a prefix. The temp name is
    pid+thread-unique — the async checkpoint writer and the main thread
    share a pid and may both touch e.g. the ``latest`` marker.

    ``reclaim_stale`` sweeps temps a killed writer left for THIS target —
    enable it ONLY at single-writer call sites (the rank-0-gated ``latest``
    marker): on a shared filesystem a collective save has every rank
    writing the same staged file, and a sweep there would delete a peer's
    live temp mid-write (staged-dir temp leaks are reclaimed wholesale by
    ``clear_stale_staging`` instead)."""
    path = os.path.abspath(path)
    if reclaim_stale:
        parent, base = os.path.split(path)
        try:
            for name in os.listdir(parent or "."):
                if name.startswith(base + ".tmp-"):
                    try:
                        os.remove(os.path.join(parent, name))
                    except OSError:
                        pass
        except OSError:
            pass
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if do_fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if do_fsync:
        fsync_dir(os.path.dirname(path))


def atomic_write_text(
    path: str, text: str, do_fsync: bool = True, reclaim_stale: bool = False
) -> None:
    atomic_write_bytes(
        path, text.encode("utf-8"), do_fsync=do_fsync, reclaim_stale=reclaim_stale
    )


def staging_dir(final_path: str) -> str:
    """The staging sibling for a checkpoint directory. DETERMINISTIC (no
    pid): a multi-process orbax save is a collective — every rank must
    hand the storage layer the SAME path or each writes its shards into a
    private dir. Stale staging from a killed save is reclaimed by
    ``clear_stale_staging`` before the next save of the tag; concurrent
    same-tag saves within one process are serialized by the engine (sync
    saves fence the async writer; the writer itself is single-threaded)."""
    final_path = os.path.abspath(final_path)
    return f"{final_path}.staging"


def restore_orphaned_trash(save_dir: str) -> int:
    """Undo a kill inside ``commit_staged``'s re-save window: between the
    move-aside of the existing checkpoint and the staging rename, the
    previous checkpoint exists only as ``<tag>.trash-<pid>``. If the tag
    itself is missing, the trash IS the newest valid state — rename it
    back. Returns how many tags were restored."""
    if not os.path.isdir(save_dir):
        return 0
    restored = 0
    for name in os.listdir(save_dir):
        m = _TRASH_NAME.match(name)
        if not m:
            continue
        final = os.path.join(save_dir, m.group(1))
        trash = os.path.join(save_dir, name)
        if os.path.exists(final):
            continue
        if os.path.isfile(os.path.join(trash, "meta.pkl")):
            os.rename(trash, final)
            fsync_dir(save_dir)
            restored += 1
    return restored


def clear_stale_staging(final_path: str) -> None:
    """Reclaim staging/trash garbage left by killed saves of this
    checkpoint — after first restoring a trash dir whose final is missing
    (the commit-window kill: deleting it would destroy the only copy)."""
    final_path = os.path.abspath(final_path)
    parent, base = os.path.split(final_path)
    if not os.path.isdir(parent):
        return
    restore_orphaned_trash(parent)
    for name in os.listdir(parent):
        if name.startswith(base + ".staging") or name.startswith(base + ".trash-"):
            shutil.rmtree(os.path.join(parent, name), ignore_errors=True)


def commit_staged(staging: str, final_path: str, do_fsync: bool = True) -> None:
    """The commit: one atomic directory rename. An existing checkpoint under
    ``final_path`` (a re-save of the same tag) is moved aside first and
    deleted after — the window where neither exists is covered by the OTHER
    valid checkpoints ``find_latest_valid`` scans."""
    final_path = os.path.abspath(final_path)
    if not os.path.isdir(staging):
        raise CheckpointCorruptError(f"no staged checkpoint at {staging}")
    chaos.point("ckpt.pre_commit", path=staging)
    trash = None
    if os.path.exists(final_path):
        trash = f"{final_path}.trash-{os.getpid()}"
        os.rename(final_path, trash)
        # the one instant a re-saved tag has NO directory under its name:
        # between the two renames. A kill here leaves the previous
        # checkpoint as <tag>.trash-<pid>, which restore_orphaned_trash
        # (run by the next save AND by list_valid_tags/auto_resume)
        # renames back — the window is recoverable, and this injection
        # point proves it in the crash matrix.
        chaos.point("ckpt.mid_commit", path=trash)
    os.rename(staging, final_path)
    if do_fsync:
        fsync_dir(os.path.dirname(final_path))
    if trash is not None:
        shutil.rmtree(trash, ignore_errors=True)
    chaos.point("ckpt.post_commit", path=final_path)


def is_complete_checkpoint(path: str) -> bool:
    """A committed, non-torn checkpoint directory: the metadata exists and —
    for checkpoints written by the staged engine — the ``_COMPLETE``
    sentinel does too. Directories still carrying a staging/trash suffix
    are never checkpoints."""
    base = os.path.basename(os.path.abspath(path))
    if ".staging" in base or ".trash-" in base or base.endswith(".tmp"):
        return False
    if not os.path.isdir(path):
        return False
    if not os.path.isfile(os.path.join(path, "meta.pkl")):
        return False
    marker = os.path.join(path, COMPLETE_MARKER)
    # pre-atomic-era checkpoints have no sentinel; meta.pkl alone suffices
    # for them, but a sentinel file that exists must not be empty garbage
    return True if not os.path.exists(marker) else os.path.isfile(marker)


def tag_sort_key(save_dir: str, tag: str) -> Tuple[int, float]:
    """Newest-checkpoint ordering: the trailing integer of the tag
    (``global_step120`` -> 120) wins; tags without one fall back to the
    directory mtime."""
    m = _STEP_TAG.search(tag)
    step = int(m.group(1)) if m else -1
    try:
        mtime = os.path.getmtime(os.path.join(save_dir, tag))
    except OSError:
        mtime = 0.0
    return (step, mtime)


def list_valid_tags(save_dir: str) -> List[str]:
    """Every committed checkpoint tag under ``save_dir``, oldest first.
    Repairs the commit-window kill first (an orphaned ``.trash-`` dir is
    the newest valid state for its tag)."""
    if not os.path.isdir(save_dir):
        return []
    restore_orphaned_trash(save_dir)
    tags = [
        name
        for name in os.listdir(save_dir)
        if is_complete_checkpoint(os.path.join(save_dir, name))
    ]
    tags.sort(key=lambda t: tag_sort_key(save_dir, t))
    return tags


def find_latest_valid(save_dir: str) -> Optional[str]:
    """The newest VALID checkpoint tag — the ``auto_resume`` discovery path.

    The ``latest`` marker is only a hint: a kill between commit and the
    marker update leaves a newer valid checkpoint the marker does not name,
    and a corrupted tree could leave a marker naming a torn one. Scanning +
    validating covers both: no kill instant can make this return a torn
    checkpoint, and the newest committed one always wins."""
    tags = list_valid_tags(save_dir)
    return tags[-1] if tags else None


def write_latest_marker(save_dir: str, tag: str, do_fsync: bool = True) -> None:
    """Atomically point ``latest`` at ``tag``. Call only after commit, and
    only from one writer at a time (the engine rank-0-gates it) — which is
    what makes reclaiming a killed writer's stale temps safe here."""
    os.makedirs(save_dir, exist_ok=True)
    atomic_write_text(
        os.path.join(save_dir, LATEST_NAME), tag, do_fsync=do_fsync,
        reclaim_stale=True,
    )


def read_latest_marker(save_dir: str) -> Optional[str]:
    path = os.path.join(save_dir, LATEST_NAME)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return f.read().strip() or None
