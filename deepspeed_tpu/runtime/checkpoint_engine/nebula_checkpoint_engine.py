"""Async tiered checkpoint engine.

Counterpart of the reference's ``NebulaCheckpointEngine``
(``runtime/checkpoint_engine/nebula_checkpoint_engine.py:20`` + config
``deepspeed/nebula/config.py``): saves return immediately — state is
snapshotted to host memory and persisted by a background thread (tier-1),
so the train loop never blocks on filesystem latency; ``commit`` fences the
pending write (the reference's persistence handshake)."""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import CheckpointEngine
from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
    OrbaxCheckpointEngine,
)
from deepspeed_tpu.utils.logging import logger


class NebulaCheckpointEngine(CheckpointEngine):
    def __init__(self, config_params=None, enable_nebula_load: bool = True):
        super().__init__(config_params)
        self.inner = OrbaxCheckpointEngine(config_params)
        self.enable_nebula_load = enable_nebula_load
        self._pending: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def create(self, tag: str) -> None:
        self.inner.create(tag)

    def save(self, state_dict: Any, path: str) -> None:
        self._wait()
        # tier-1 snapshot: pull device state to host NOW (cheap vs disk),
        # then persist in the background
        host_state = jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if hasattr(x, "devices") else x, state_dict
        )

        def _persist():
            try:
                self.inner.save(host_state, path)
            except BaseException as e:  # surfaced at the next fence
                self._error = e

        self._pending = threading.Thread(target=_persist, daemon=True)
        self._pending.start()
        logger.info(f"nebula: async persisting checkpoint to {path}")

    def load(self, path: str, map_location=None) -> Any:
        self._wait()
        return self.inner.load(path, map_location)

    def commit(self, tag: str) -> bool:
        self._wait()
        return self.inner.commit(tag)

    def _wait(self) -> None:
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"nebula background persist failed: {err}")
