"""Checkpoint engine ABC (reference:
``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py:9``)."""

from __future__ import annotations


class CheckpointEngine:
    def __init__(self, config_params=None):
        self.config_params = config_params

    def create(self, tag: str) -> None:
        """Log/prepare for a checkpoint under ``tag``."""

    def save(self, state_dict, path: str) -> None:
        raise NotImplementedError

    def load(self, path: str, map_location=None, target=None):
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        """Flush/finalize everything saved under ``tag``."""
        return True
