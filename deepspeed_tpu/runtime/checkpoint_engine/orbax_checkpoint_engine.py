"""Orbax-backed checkpoint engine.

TPU-native counterpart of the reference's ``TorchCheckpointEngine``
(torch.save/load) — sharded arrays are written with
``orbax.checkpoint``/tensorstore so every host writes only its addressable
shards, which is the reference's per-rank ``zero_pp_rank_*`` file scheme done
by the storage layer instead of by hand. Non-array metadata rides a side
pickle/JSON.

Atomicity (``atomic.py``): ``save()`` stages the whole checkpoint under a
``<path>.staging`` sibling (DETERMINISTIC across ranks — a multi-process
orbax save is collective, every rank must target one shared dir; in-process
same-tag concurrency is serialized by the engine) — arrays, then metadata,
then a ``_COMPLETE`` sentinel — and ``commit()`` renames it into place in
one atomic directory rename. A ``kill -9`` at any instant therefore leaves
either the previous committed checkpoint or the new one, never a torn mix;
staging garbage from killed saves is reclaimed on the next save of the same
tag. ``load()`` raises :class:`CheckpointCorruptError` with a named cause on
any torn layout (missing ``meta.pkl``, undecodable pickle, metadata that
references an array payload that is not there) instead of surfacing a
``FileNotFoundError`` from deep inside pickle/tensorstore.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.atomic import (
    COMPLETE_MARKER,
    CheckpointCorruptError,
    atomic_write_bytes,
    clear_stale_staging,
    commit_staged,
    staging_dir,
)
from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import CheckpointEngine
from deepspeed_tpu.utils import chaos
from deepspeed_tpu.utils.logging import logger


def _is_array_leaf(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


class OrbaxCheckpointEngine(CheckpointEngine):
    """Saves a state pytree: arrays via orbax, the rest via pickle."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()
        # tag/basename -> (staging_dir, final_path), staged by save(),
        # renamed into place by commit(). Locked: the async writer thread
        # and a synchronous save on the main thread may share one engine,
        # and an unlocked copy-then-clear could wipe a concurrently staged
        # entry without ever committing it.
        self._staged: Dict[str, Tuple[str, str]] = {}
        self._staged_lock = threading.Lock()

    def create(self, tag: str) -> None:
        logger.info(f"[OrbaxCheckpointEngine] Saving checkpoint under tag {tag}")

    def save(self, state_dict: Any, path: str) -> None:
        path = os.path.abspath(path)
        arrays = {}
        meta = {}

        def split(prefix: str, obj):
            if isinstance(obj, dict):
                return {k: split(f"{prefix}/{k}", v) for k, v in obj.items()}
            if isinstance(obj, (list, tuple)):
                items = [split(f"{prefix}/{i}", v) for i, v in enumerate(obj)]
                return {"__seq__": "tuple" if isinstance(obj, tuple) else "list", "items": items}
            if hasattr(obj, "items") and not _is_array_leaf(obj):  # FrozenDict etc.
                return {k: split(f"{prefix}/{k}", v) for k, v in obj.items()}
            if _is_array_leaf(obj):
                arrays[prefix] = obj
                return {"__array_ref__": prefix}
            meta[prefix] = obj
            return {"__meta_ref__": prefix}

        skeleton = split("root", state_dict)
        clear_stale_staging(path)
        staging = staging_dir(path)
        os.makedirs(staging, exist_ok=True)
        if arrays:
            self._ckptr.save(os.path.join(staging, "arrays"), arrays, force=True)
            self._ckptr.wait_until_finished()
        chaos.point("ckpt.mid_array_write", path=staging)
        # staged writes are invisible until commit, but each file is still
        # written atomically so a re-entrant save over live staging (cannot
        # happen today; belt and braces) never tears it
        atomic_write_bytes(
            os.path.join(staging, "meta.pkl"),
            pickle.dumps({"skeleton": skeleton, "meta": meta}),
        )
        # the sentinel is LAST: a staging dir without it is by definition a
        # torn, never-committable snapshot
        atomic_write_bytes(os.path.join(staging, COMPLETE_MARKER), b"ok")
        with self._staged_lock:
            self._staged[os.path.basename(path)] = (staging, path)

    def load(self, path: str, map_location=None, target=None):  # noqa: ARG002
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise CheckpointCorruptError(f"no checkpoint directory at {path}")
        meta_path = os.path.join(path, "meta.pkl")
        if not os.path.isfile(meta_path):
            raise CheckpointCorruptError(
                f"torn checkpoint at {path}: meta.pkl is missing (the save "
                "was killed before commit, or the directory was truncated)"
            )
        try:
            with open(meta_path, "rb") as f:
                blob = pickle.load(f)
            skeleton, meta = blob["skeleton"], blob["meta"]
        except CheckpointCorruptError:
            raise
        except Exception as e:
            raise CheckpointCorruptError(
                f"torn checkpoint at {path}: meta.pkl is unreadable ({type(e).__name__}: {e})"
            ) from e

        def has_array_refs(obj) -> bool:
            if isinstance(obj, dict):
                if "__array_ref__" in obj:
                    return True
                it = obj["items"] if "__seq__" in obj else obj.values()
                return any(has_array_refs(v) for v in it)
            return False

        arrays_path = os.path.join(path, "arrays")
        arrays = {}
        if has_array_refs(skeleton):
            if not os.path.exists(arrays_path):
                raise CheckpointCorruptError(
                    f"torn checkpoint at {path}: meta.pkl references an array "
                    "payload but arrays/ is missing"
                )
            try:
                arrays = self._ckptr.restore(arrays_path)
            except Exception as e:
                raise CheckpointCorruptError(
                    f"torn checkpoint at {path}: array payload unreadable "
                    f"({type(e).__name__}: {e})"
                ) from e

        # reassemble
        def join(obj):
            if isinstance(obj, dict) and "__array_ref__" in obj:
                return arrays[obj["__array_ref__"]]
            if isinstance(obj, dict) and "__meta_ref__" in obj:
                return meta[obj["__meta_ref__"]]
            if isinstance(obj, dict) and "__seq__" in obj:
                seq = [join(v) for v in obj["items"]]
                return tuple(seq) if obj["__seq__"] == "tuple" else seq
            if isinstance(obj, dict):
                return {k: join(v) for k, v in obj.items()}
            return obj

        return join(skeleton)

    def commit(self, tag: str) -> bool:
        """Rename the checkpoint staged under ``tag`` into place — THE
        atomic commit point. When ``tag`` has no staged entry of its own (a
        caller that staged under a different basename, e.g. the Nebula
        engine's tag-vs-path split), every pending entry is committed
        instead of leaked. Entries are popped under the lock, so each
        staged checkpoint is committed exactly once even when the async
        writer thread and a synchronous save share this engine."""
        with self._staged_lock:
            if tag in self._staged:
                pending = {tag: self._staged.pop(tag)}
            else:
                pending, self._staged = self._staged, {}
        for staging, final in pending.values():
            commit_staged(staging, final)
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is ready")
        return True

    def discard_staged(self, tag: str) -> None:
        """Forget a staged entry WITHOUT touching disk — the non-zero
        ranks of a collective save call this while rank 0 commits: all
        ranks staged into the same shared directory, so exactly one
        process may perform (and must not race on) the rename."""
        with self._staged_lock:
            self._staged.pop(tag, None)
