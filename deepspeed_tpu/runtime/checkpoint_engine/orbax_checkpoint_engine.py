"""Orbax-backed checkpoint engine.

TPU-native counterpart of the reference's ``TorchCheckpointEngine``
(torch.save/load) — sharded arrays are written with
``orbax.checkpoint``/tensorstore so every host writes only its addressable
shards, which is the reference's per-rank ``zero_pp_rank_*`` file scheme done
by the storage layer instead of by hand. Non-array metadata rides a side
pickle/JSON.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import CheckpointEngine
from deepspeed_tpu.utils.logging import logger


def _is_array_leaf(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


class OrbaxCheckpointEngine(CheckpointEngine):
    """Saves a state pytree: arrays via orbax, the rest via pickle."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def create(self, tag: str) -> None:
        logger.info(f"[OrbaxCheckpointEngine] Saving checkpoint under tag {tag}")

    def save(self, state_dict: Any, path: str) -> None:
        path = os.path.abspath(path)
        arrays = {}
        meta = {}

        def split(prefix: str, obj):
            if isinstance(obj, dict):
                return {k: split(f"{prefix}/{k}", v) for k, v in obj.items()}
            if isinstance(obj, (list, tuple)):
                items = [split(f"{prefix}/{i}", v) for i, v in enumerate(obj)]
                return {"__seq__": "tuple" if isinstance(obj, tuple) else "list", "items": items}
            if hasattr(obj, "items") and not _is_array_leaf(obj):  # FrozenDict etc.
                return {k: split(f"{prefix}/{k}", v) for k, v in obj.items()}
            if _is_array_leaf(obj):
                arrays[prefix] = obj
                return {"__array_ref__": prefix}
            meta[prefix] = obj
            return {"__meta_ref__": prefix}

        skeleton = split("root", state_dict)
        os.makedirs(path, exist_ok=True)
        if arrays:
            self._ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
            self._ckptr.wait_until_finished()
        with open(os.path.join(path, "meta.pkl"), "wb") as f:
            pickle.dump({"skeleton": skeleton, "meta": meta}, f)

    def load(self, path: str, map_location=None, target=None):  # noqa: ARG002
        path = os.path.abspath(path)
        with open(os.path.join(path, "meta.pkl"), "rb") as f:
            blob = pickle.load(f)
        skeleton, meta = blob["skeleton"], blob["meta"]
        arrays_path = os.path.join(path, "arrays")
        arrays = {}
        if os.path.exists(arrays_path):
            arrays = self._ckptr.restore(arrays_path)

        # reassemble
        def join(obj):
            if isinstance(obj, dict) and "__array_ref__" in obj:
                return arrays[obj["__array_ref__"]]
            if isinstance(obj, dict) and "__meta_ref__" in obj:
                return meta[obj["__meta_ref__"]]
            if isinstance(obj, dict) and "__seq__" in obj:
                seq = [join(v) for v in obj["items"]]
                return tuple(seq) if obj["__seq__"] == "tuple" else seq
            if isinstance(obj, dict):
                return {k: join(v) for k, v in obj.items()}
            return obj

        return join(skeleton)

    def commit(self, tag: str) -> bool:
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is ready")
        return True
