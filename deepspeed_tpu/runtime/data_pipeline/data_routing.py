"""Random layerwise token dropping (random-LTD).

Counterpart of the reference's ``deepspeed/runtime/data_pipeline/data_routing/``
(``basic_layer.py RandomLayerTokenDrop`` + the native gather/scatter kernels
``csrc/random_ltd/``): during training, middle layers process only a random
subset of tokens; the untouched tokens bypass the layer and are scattered
back — cutting per-layer FLOPs while the schedule grows the kept-token count
to full length by the end of training.

On TPU the gather/scatter is ``jnp.take_along_axis`` /
``.at[].set`` — static kept-count per compiled program (the scheduler's
values bucket compilation, like the reference's seqlen schedule).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Kept-token schedule (reference ``scheduler.py``): linear increase
    from ``start_token_num`` to the full ``max_token_num`` over
    ``total_layer_token_steps``."""

    def __init__(self, start_token_num: int, max_token_num: int, total_steps: int, step_size: int = 16):
        self.start = start_token_num
        self.max = max_token_num
        self.total = max(total_steps, 1)
        self.step_size = step_size
        self.current = start_token_num

    def update(self, global_step: int) -> int:
        frac = min(1.0, global_step / self.total)
        if frac >= 1.0:
            # snap to full length even when max is not a step_size multiple
            self.current = self.max
            return self.current
        n = self.start + (self.max - self.start) * frac
        n = int(n // self.step_size) * self.step_size
        self.current = max(self.start, min(self.max, n))
        return self.current

    def state_dict(self) -> Dict[str, Any]:
        return {"current": self.current}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.current = sd["current"]


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def sample_layer_token_indices(rng, n_layers: int, batch: int, seq_len: int, kept: int) -> jnp.ndarray:
    """[n_layers, B, kept] sorted random token indices — each LTD layer
    draws its OWN subset (the 'layerwise' in random-LTD; sorted so position
    order — and causality — is preserved, the reference's token_sort.cu).
    One fused program: a per-layer host loop would cost n_layers dispatch
    round-trips per step on a tunneled backend."""
    scores = jax.random.uniform(rng, (n_layers, batch, seq_len))
    _, idx = jax.lax.top_k(-scores, kept)
    return jnp.sort(idx, axis=-1).astype(jnp.int32)


def random_token_select(rng, seq_len: int, kept: int, batch: int) -> jnp.ndarray:
    """[B, kept] single-layer form of ``sample_layer_token_indices``."""
    return sample_layer_token_indices(rng, 1, batch, seq_len, kept)[0]


def gather_tokens(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """[B, T, H] × [B, kept] → [B, kept, H] (csrc/random_ltd/gather_scatter.cu)."""
    return jnp.take_along_axis(x, idx[..., None], axis=1)


def scatter_tokens(full: jnp.ndarray, processed: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Write processed tokens back at their positions; untouched tokens keep
    the bypass value."""
    B = full.shape[0]
    b_idx = jnp.arange(B)[:, None]
    return full.at[b_idx, idx].set(processed)


class RandomLayerTokenDrop:
    """Wrap a layer fn so it runs on a random token subset
    (reference ``basic_layer.py RandomLayerTokenDrop``)."""

    def __init__(self, layer_fn, scheduler: RandomLTDScheduler):
        self.layer_fn = layer_fn
        self.scheduler = scheduler

    def __call__(self, params, x: jnp.ndarray, rng, train: bool = True, **kwargs):
        kept = self.scheduler.current
        T = x.shape[1]
        if not train or kept >= T:
            return self.layer_fn(params, x, **kwargs)
        idx = random_token_select(rng, T, kept, x.shape[0])
        sub = gather_tokens(x, idx)
        out = self.layer_fn(params, sub, **kwargs)
        return scatter_tokens(x, out, idx)
