"""Curriculum-aware data sampling.

Counterpart of the reference's ``data_sampling/data_sampler.py``
(``DeepSpeedDataSampler``): a deterministic distributed sampler whose batch
composition can follow a difficulty metric — samples are bucketed by a
difficulty value and early training draws from the easy buckets
(curriculum), annealing to the full distribution.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np


class DistributedSampler:
    """Deterministic per-rank sampler (torch DistributedSampler semantics —
    what ``deepspeed_io`` uses for plain DP)."""

    def __init__(self, dataset_len: int, num_replicas: int = 1, rank: int = 0, shuffle: bool = True, seed: int = 0, drop_last: bool = False):
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = (dataset_len + num_replicas - 1) // num_replicas
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rs = np.random.RandomState(self.seed + self.epoch)
            indices = rs.permutation(self.dataset_len).tolist()
        else:
            indices = list(range(self.dataset_len))
        if not self.drop_last:
            pad = self.total_size - len(indices)
            indices += indices[:pad]
        else:
            indices = indices[: self.total_size]
        return iter(indices[self.rank : self.total_size : self.num_replicas])

    def __len__(self) -> int:
        return self.num_samples


class DeepSpeedDataSampler:
    """Curriculum sampler (reference ``DeepSpeedDataSampler``): given a
    per-sample difficulty array and a ``CurriculumScheduler``, each epoch
    draws only samples whose difficulty ≤ the current threshold."""

    def __init__(
        self,
        difficulties: Sequence[float],
        curriculum_scheduler,
        num_replicas: int = 1,
        rank: int = 0,
        seed: int = 0,
        global_batch_size: int = 1,
    ):
        self.difficulties = np.asarray(difficulties)
        self.scheduler = curriculum_scheduler
        self.num_replicas = num_replicas
        self.rank = rank
        self.seed = seed
        self.global_batch_size = global_batch_size
        self.consumed_samples = 0

    def eligible_indices(self) -> np.ndarray:
        threshold = self.scheduler.get_current_difficulty()
        idx = np.nonzero(self.difficulties <= threshold)[0]
        if idx.size == 0:
            idx = np.argsort(self.difficulties)[: self.global_batch_size]
        return idx

    def __iter__(self) -> Iterator[int]:
        step = 0
        while True:
            self.scheduler.update_difficulty(step)
            pool = self.eligible_indices()
            rs = np.random.RandomState(self.seed + step)
            batch = rs.choice(pool, size=self.global_batch_size, replace=pool.size < self.global_batch_size)
            for i in batch[self.rank :: self.num_replicas]:
                yield int(i)
            self.consumed_samples += self.global_batch_size
            step += 1

    def state_dict(self):
        return {"consumed_samples": self.consumed_samples}

    def load_state_dict(self, sd):
        self.consumed_samples = sd["consumed_samples"]


def sampler_from_analysis(
    save_path: str,
    metric_name: str,
    curriculum_scheduler,
    num_replicas: int = 1,
    rank: int = 0,
    seed: int = 0,
    global_batch_size: int = 1,
) -> DeepSpeedDataSampler:
    """Build the curriculum sampler from a ``DataAnalyzer`` run's
    ``sample_to_metric`` table — the map-reduce → sampler hookup the
    reference wires through its index files."""
    from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import (
        DataAnalyzer,
    )

    analyzer = DataAnalyzer([], metric_names=[], metric_functions=[], metric_types=[], save_path=save_path)
    difficulties = analyzer.load_sample_to_metric(metric_name)
    return DeepSpeedDataSampler(
        difficulties,
        curriculum_scheduler,
        num_replicas=num_replicas,
        rank=rank,
        seed=seed,
        global_batch_size=global_batch_size,
    )
