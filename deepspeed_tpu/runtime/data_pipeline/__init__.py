"""Data-efficiency pipeline (reference: ``deepspeed/runtime/data_pipeline/``)."""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_routing import (
    RandomLayerTokenDrop,
    RandomLTDScheduler,
)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
    DeepSpeedDataSampler,
    DistributedSampler,
)
