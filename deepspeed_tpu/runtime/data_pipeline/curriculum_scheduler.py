"""Curriculum-learning scheduler.

Counterpart of the reference's ``CurriculumScheduler``
(``deepspeed/runtime/data_pipeline/curriculum_scheduler.py``): maps the
global step to a difficulty value (typically sequence length) under the
``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` / ``custom``
schedules. The engine truncates each batch's sequence dim to the current
difficulty (the reference injects a ``curriculum_seqlen`` kwarg,
engine.py:1779-1782 — with functional batches, truncation is the cleaner
equivalent and keeps the jitted step's shape bucketing small).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

CURRICULUM_LEARNING_MIN_DIFFICULTY = "min_difficulty"
CURRICULUM_LEARNING_MAX_DIFFICULTY = "max_difficulty"
CURRICULUM_LEARNING_SCHEDULE_TYPE = "schedule_type"
CURRICULUM_LEARNING_SCHEDULE_CONFIG = "schedule_config"
CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR = "fixed_linear"
CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT = "fixed_root"
CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE = "fixed_discrete"
CURRICULUM_LEARNING_SCHEDULE_CUSTOM = "custom"
CURRICULUM_LEARNING_SCHEDULE_TOTAL_STEP = "total_curriculum_step"
CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP = "difficulty_step"
CURRICULUM_LEARNING_SCHEDULE_ROOT_DEGREE = "root_degree"
CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY = "difficulty"
CURRICULUM_LEARNING_SCHEDULE_MAX_STEP = "max_step"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        for key in (
            CURRICULUM_LEARNING_MIN_DIFFICULTY,
            CURRICULUM_LEARNING_MAX_DIFFICULTY,
            CURRICULUM_LEARNING_SCHEDULE_TYPE,
        ):
            assert key in config, f"curriculum learning config missing '{key}'"
        self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY] = config[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY] = config[CURRICULUM_LEARNING_MAX_DIFFICULTY]
        self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE] = config[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        self.state["current_difficulty"] = config[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        schedule_type = config[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        schedule_config = config.get(CURRICULUM_LEARNING_SCHEDULE_CONFIG, {})
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None

        if schedule_type == CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR:
            assert CURRICULUM_LEARNING_SCHEDULE_TOTAL_STEP in schedule_config
            assert CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP in schedule_config
        elif schedule_type == CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT:
            assert CURRICULUM_LEARNING_SCHEDULE_TOTAL_STEP in schedule_config
            assert CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP in schedule_config
            assert CURRICULUM_LEARNING_SCHEDULE_ROOT_DEGREE in schedule_config
        elif schedule_type == CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE:
            assert CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY in schedule_config
            assert CURRICULUM_LEARNING_SCHEDULE_MAX_STEP in schedule_config
            assert len(schedule_config[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY]) > 0
            assert len(schedule_config[CURRICULUM_LEARNING_SCHEDULE_MAX_STEP]) == len(
                schedule_config[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY]
            ) - 1
        elif schedule_type == CURRICULUM_LEARNING_SCHEDULE_CUSTOM:
            pass
        else:
            raise RuntimeError(f"Unsupported curriculum schedule type {schedule_type}")
        self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG] = schedule_config

    # --- reference surface ----------------------------------------------
    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty: int) -> None:
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, schedule_function: Callable[[int], int]) -> None:
        self.custom_get_difficulty = schedule_function

    def get_state(self) -> Dict[str, Any]:
        return self.state

    def set_state(self, state: Dict[str, Any]) -> None:
        self.state = state

    def _fixed_linear(self, global_steps: int) -> int:
        cfg = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        mind = self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        maxd = self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY]
        total = cfg[CURRICULUM_LEARNING_SCHEDULE_TOTAL_STEP]
        stepd = cfg[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP]
        next_difficulty = mind + (maxd - mind) * min(1.0, global_steps / total)
        next_difficulty = int(next_difficulty / stepd) * stepd
        return max(mind, min(maxd, next_difficulty))

    def _fixed_root(self, global_steps: int) -> int:
        cfg = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        mind = self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        maxd = self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY]
        total = cfg[CURRICULUM_LEARNING_SCHEDULE_TOTAL_STEP]
        stepd = cfg[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP]
        degree = cfg[CURRICULUM_LEARNING_SCHEDULE_ROOT_DEGREE]
        frac = min(1.0, global_steps / total) ** (1.0 / degree)
        next_difficulty = mind + (maxd - mind) * frac
        next_difficulty = int(next_difficulty / stepd) * stepd
        return max(mind, min(maxd, next_difficulty))

    def _fixed_discrete(self, global_steps: int) -> int:
        cfg = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        difficulties = cfg[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY]
        max_steps = cfg[CURRICULUM_LEARNING_SCHEDULE_MAX_STEP]
        for d, s in zip(difficulties, max_steps):
            if global_steps <= s:
                return d
        return difficulties[-1]

    def update_difficulty(self, global_steps: int) -> int:
        t = self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        if t == CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR:
            d = self._fixed_linear(global_steps)
        elif t == CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT:
            d = self._fixed_root(global_steps)
        elif t == CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE:
            d = self._fixed_discrete(global_steps)
        else:
            assert self.custom_get_difficulty is not None, "custom schedule needs a function"
            d = self.custom_get_difficulty(global_steps)
        self.state["current_difficulty"] = d
        return d
