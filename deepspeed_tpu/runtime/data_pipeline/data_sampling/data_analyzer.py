"""Offline dataset analysis for curriculum / data-efficiency sampling.

Counterpart of the reference's ``DataAnalyzer``
(``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py``): a
map-reduce over the dataset computing per-sample difficulty metrics. The
map phase shards samples across workers, each writing per-metric index
files; the reduce phase merges them into the two lookup tables the
curriculum sampler consumes:

* ``<metric>_sample_to_metric`` — metric value per sample index;
* ``<metric>_metric_to_sample`` — sample indices grouped per metric value
  (an ``MMapIndexedDataset``: one "sequence" of sample ids per value).

Metric types, as in the reference: ``single_value_per_sample`` (one number
per sample, e.g. seqlen) and ``accumulate_value_over_samples`` (one running
total, e.g. token histogram).
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)
from deepspeed_tpu.utils.logging import logger


def _metric_prefix(save_path: str, metric_name: str, kind: str, worker_id: Optional[int] = None) -> str:
    base = os.path.join(save_path, metric_name)
    os.makedirs(base, exist_ok=True)
    suffix = f"_worker{worker_id}" if worker_id is not None else ""
    return os.path.join(base, f"{metric_name}_{kind}{suffix}")


class DataAnalyzer:
    def __init__(
        self,
        dataset,
        num_workers: int = 1,
        metric_names: Sequence[str] = (),
        metric_functions: Sequence[Callable] = (),
        metric_types: Sequence[str] = (),
        save_path: str = "./data_analysis",
        batch_size: int = 1,  # noqa: ARG002 - parity; map iterates samples
        metric_dtypes: Optional[Sequence] = None,
    ):
        assert len(metric_names) == len(metric_functions) == len(metric_types)
        for t in metric_types:
            if t not in ("single_value_per_sample", "accumulate_value_over_samples"):
                raise ValueError(f"unknown metric_type {t!r}")
        self.dataset = dataset
        self.num_workers = max(1, num_workers)
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types)
        self.metric_dtypes = list(metric_dtypes or [np.int64] * len(metric_names))
        self.save_path = save_path

    # --- map -------------------------------------------------------------
    def _worker_range(self, worker_id: int):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        return range(worker_id * per, min(n, (worker_id + 1) * per))

    def run_map(self, worker_id: int = 0) -> None:
        """One worker's shard: compute every metric for its sample range and
        persist per-worker partial results."""
        idx_range = self._worker_range(worker_id)
        singles = {m: [] for m, t in zip(self.metric_names, self.metric_types) if t == "single_value_per_sample"}
        accums = {m: None for m, t in zip(self.metric_names, self.metric_types) if t == "accumulate_value_over_samples"}
        for i in idx_range:
            sample = self.dataset[i]
            for name, fn, mtype in zip(self.metric_names, self.metric_functions, self.metric_types):
                value = fn(sample)
                if mtype == "single_value_per_sample":
                    singles[name].append(int(value))
                else:
                    arr = np.asarray(value)
                    accums[name] = arr if accums[name] is None else accums[name] + arr
        os.makedirs(self.save_path, exist_ok=True)
        for name, values in singles.items():
            np.save(
                _metric_prefix(self.save_path, name, "sample_to_metric", worker_id) + ".npy",
                np.asarray(values, dtype=np.int64),
            )
            with open(_metric_prefix(self.save_path, name, "range", worker_id) + ".json", "w") as f:
                json.dump({"start": idx_range.start, "stop": idx_range.stop}, f)
        for name, total in accums.items():
            np.save(
                _metric_prefix(self.save_path, name, "accumulate", worker_id) + ".npy",
                np.asarray(0 if total is None else total),
            )

    # --- reduce ----------------------------------------------------------
    def run_reduce(self) -> None:
        """Merge worker partials into the final lookup tables."""
        for name, mtype in zip(self.metric_names, self.metric_types):
            if mtype == "single_value_per_sample":
                parts = []
                for w in range(self.num_workers):
                    vals = np.load(
                        _metric_prefix(self.save_path, name, "sample_to_metric", w) + ".npy"
                    )
                    with open(_metric_prefix(self.save_path, name, "range", w) + ".json") as f:
                        rng = json.load(f)
                    parts.append((rng["start"], vals))
                parts.sort()
                sample_to_metric = np.concatenate([v for _, v in parts])
                np.save(
                    _metric_prefix(self.save_path, name, "sample_to_metric") + ".npy",
                    sample_to_metric,
                )
                # metric_to_sample: one sequence of sample ids per metric value
                prefix = _metric_prefix(self.save_path, name, "metric_to_sample")
                builder = MMapIndexedDatasetBuilder(prefix + ".bin", dtype=np.int64)
                values = np.unique(sample_to_metric)
                for v in values:
                    builder.add_item(np.nonzero(sample_to_metric == v)[0].astype(np.int64))
                    builder.end_document()
                builder.finalize(prefix + ".idx")
                np.save(_metric_prefix(self.save_path, name, "metric_values") + ".npy", values)
            else:
                total = None
                for w in range(self.num_workers):
                    part = np.load(_metric_prefix(self.save_path, name, "accumulate", w) + ".npy")
                    total = part if total is None else total + part
                np.save(_metric_prefix(self.save_path, name, "accumulate") + ".npy", total)
        logger.info(f"DataAnalyzer: reduced {len(self.metric_names)} metric(s) → {self.save_path}")

    def run(self) -> None:
        """Single-process convenience: all map shards then reduce."""
        for w in range(self.num_workers):
            self.run_map(w)
        self.run_reduce()

    # --- consumption ------------------------------------------------------
    def load_sample_to_metric(self, metric_name: str) -> np.ndarray:
        return np.load(_metric_prefix(self.save_path, metric_name, "sample_to_metric") + ".npy")

    def load_metric_to_sample(self, metric_name: str) -> MMapIndexedDataset:
        return MMapIndexedDataset(_metric_prefix(self.save_path, metric_name, "metric_to_sample"))

    def load_metric_values(self, metric_name: str) -> np.ndarray:
        return np.load(_metric_prefix(self.save_path, metric_name, "metric_values") + ".npy")

    def load_accumulate(self, metric_name: str) -> np.ndarray:
        return np.load(_metric_prefix(self.save_path, metric_name, "accumulate") + ".npy")
