"""Megatron-style binary indexed datasets, mmap flavor.

BIT-COMPATIBLE with the reference's on-disk format
(``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py:369``
``MMapIndexedDataset``): corpora tokenized by Megatron-LM / the reference's
data tooling load directly, and datasets built here load there.

Layout: ``<prefix>.bin`` holds the raw token stream; ``<prefix>.idx`` is

    b'MMIDIDX\\x00\\x00' | <Q version=1> | <B dtype code> |
    <Q n_sequences> | <Q n_docs> |
    sizes  int32[n_sequences]   (elements per sequence)
    pointers int64[n_sequences] (byte offset of each sequence in .bin)
    doc_idx int64[n_docs]       (sequence index where each document starts)
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

_HDR_MAGIC = b"MMIDIDX\x00\x00"

dtypes = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.float64,
    7: np.double,
    8: np.uint16,
    9: np.uint32,
    10: np.uint64,
}


def code(dtype) -> int:
    for c, dt in dtypes.items():
        if dt == dtype:
            return c
    raise ValueError(f"unsupported dtype {dtype}")


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDataset:
    """Random-access reader over the mmap'd .bin/.idx pair."""

    class Index:
        def __init__(self, path: str):
            with open(path, "rb") as stream:
                magic = stream.read(9)
                assert magic == _HDR_MAGIC, (
                    f"{path} is not an MMIDIDX index (got {magic!r})"
                )
                (version,) = struct.unpack("<Q", stream.read(8))
                assert version == 1, f"unsupported index version {version}"
                (dtype_code,) = struct.unpack("<B", stream.read(1))
                self.dtype = dtypes[dtype_code]
                (self._len,) = struct.unpack("<Q", stream.read(8))
                (self._doc_count,) = struct.unpack("<Q", stream.read(8))
                offset = stream.tell()
            buf = memoryview(np.memmap(path, mode="r", order="C"))
            self.sizes = np.frombuffer(buf, dtype=np.int32, count=self._len, offset=offset)
            self.pointers = np.frombuffer(
                buf, dtype=np.int64, count=self._len, offset=offset + self.sizes.nbytes
            )
            self.doc_idx = np.frombuffer(
                buf,
                dtype=np.int64,
                count=self._doc_count,
                offset=offset + self.sizes.nbytes + self.pointers.nbytes,
            )

        def __len__(self) -> int:
            return self._len

    def __init__(self, prefix: str):
        self._prefix = prefix
        self._index = self.Index(index_file_path(prefix))
        self._bin = np.memmap(data_file_path(prefix), mode="r", order="C")

    def __len__(self) -> int:
        return len(self._index)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        size = int(self._index.sizes[idx])
        ptr = int(self._index.pointers[idx])
        dtype = self._index.dtype
        return np.frombuffer(self._bin, dtype=dtype, count=size, offset=ptr)

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        """Partial sequence read (reference ``get``)."""
        seq = self[idx]
        stop = None if length is None else offset + length
        return seq[offset:stop]

    @property
    def sizes(self) -> np.ndarray:
        return self._index.sizes

    @property
    def doc_idx(self) -> np.ndarray:
        return self._index.doc_idx

    @property
    def dtype(self):
        return self._index.dtype

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and os.path.exists(
            data_file_path(prefix)
        )


class MMapIndexedDatasetBuilder:
    """Streaming writer producing the reference's exact file pair."""

    def __init__(self, out_file: str, dtype=np.int32):
        self._data_file = open(out_file, "wb")
        self._dtype = dtype
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, other_prefix: str) -> None:
        """Append another dataset's sequences (reference merge for parallel
        builders)."""
        other = MMapIndexedDataset(other_prefix)
        doc_offset = len(self._sizes)
        for i in range(len(other)):
            self.add_item(other[i])
        for d in other.doc_idx[1:]:
            self._doc_idx.append(int(d) + doc_offset)

    def finalize(self, index_file: str) -> None:
        self._data_file.close()
        with open(index_file, "wb") as f:
            f.write(_HDR_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", code(self._dtype)))
            f.write(struct.pack("<Q", len(self._sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            sizes32 = np.asarray(self._sizes, dtype=np.int32)
            f.write(sizes32.tobytes(order="C"))
            itemsize = np.dtype(self._dtype).itemsize
            pointers = np.zeros(len(self._sizes), dtype=np.int64)
            if len(self._sizes) > 1:
                pointers[1:] = np.cumsum(sizes32[:-1].astype(np.int64) * itemsize)
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, dtype=np.int64).tobytes(order="C"))


def make_builder(out_file: str, impl: str = "mmap", dtype=np.int32) -> MMapIndexedDatasetBuilder:
    if impl != "mmap":
        raise NotImplementedError(f"dataset impl {impl!r}; only 'mmap' is supported")
    return MMapIndexedDatasetBuilder(out_file, dtype=dtype)


def make_dataset(prefix: str, impl: str = "mmap") -> MMapIndexedDataset:
    if impl != "mmap":
        raise NotImplementedError(f"dataset impl {impl!r}; only 'mmap' is supported")
    return MMapIndexedDataset(prefix)
