"""Data-efficiency sampling subsystem (reference:
``deepspeed/runtime/data_pipeline/data_sampling/``)."""

from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import DataAnalyzer
from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    make_builder,
    make_dataset,
)
