"""MoQ — Mixture-of-Quantization training (reference:
``deepspeed/runtime/quantize.py`` ``Quantizer``, wired by
``engine._configure_quantization`` engine.py:1330 when the compression
config's ``weight_quantization.shared_parameters`` enables quantization
with ``quantize_weight_in_forward: false``).

Semantics (reference ``Quantizer.quantize``/``mixed_fp16_quantize``): after
every optimizer step the COMPUTE-dtype weights are re-quantized while the
fp32 master stays full precision; under ``fp16_mixed_quantize`` the stored
weight is a blend ``ratio * w + (1 - ratio) * Q(w, bits)`` whose ratio
decays by ``quantize_change_ratio`` per step, annealing smoothly into the
quantized representation; the bit-width steps down from ``start_bits``
toward ``target_bits`` across ``quantize_period``-doubling windows.

TPU-native design: one jitted elementwise pass over the param tree per
step (the master→compute recast already rewrites every weight each step,
so re-quantizing after it is exactly the reference's per-step behavior).
The blend ratio is a traced scalar (no retrace as it decays); a bit-width
switch retraces once per bits value. Eigenvalue-modulated per-layer timing
(reference ``q_eigenvalue``) is not implemented — the standalone
``runtime/eigenvalue.py`` provides the measurement half."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


def quantize_symmetric(w, bits: int, groups: int = 1):
    """Per-group symmetric fake-quantization (reference q_type=0 path).

    NOT delegated to ``ops/quantizer``: that module stores int8, which
    cannot represent the >8-bit levels MoQ anneals through (start_bits is
    typically 16); this fake path computes levels in fp32 at any width."""
    flat = w.reshape(-1)
    n = flat.shape[0]
    g = groups if groups > 0 and n % groups == 0 else 1
    grouped = flat.reshape(g, n // g)
    qmax = 2.0 ** (bits - 1) - 1.0
    scale = jnp.max(jnp.abs(grouped), axis=1, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(grouped / scale), -qmax - 1, qmax)
    return (q * scale).reshape(w.shape).astype(w.dtype)


def quantize_asymmetric(w, bits: int, groups: int = 1):
    """Per-group asymmetric fake-quantization (reference q_type=1)."""
    flat = w.reshape(-1)
    n = flat.shape[0]
    g = groups if groups > 0 and n % groups == 0 else 1
    grouped = flat.reshape(g, n // g)
    lo = jnp.min(grouped, axis=1, keepdims=True)
    hi = jnp.max(grouped, axis=1, keepdims=True)
    levels = 2.0**bits - 1.0
    scale = jnp.where(hi > lo, (hi - lo) / levels, 1.0)
    q = jnp.round((grouped - lo) / scale)
    return (q * scale + lo).reshape(w.shape).astype(w.dtype)


class Quantizer:
    """Progressive in-step weight quantization (reference quantize.py:10).

    ``quantize_tree(params, step)`` returns params with every 2-D+ floating
    leaf re-quantized at the current bit-width/mix ratio."""

    def __init__(
        self,
        q_groups: int = 1,
        q_mixed_fp16: bool = False,
        q_change_ratio: float = 0.001,
        q_type: int = 0,
        q_rounding: int = 0,  # noqa: ARG002 - nearest only (stochastic n/a)
        q_verbose: bool = False,
        start_bits: int = 16,
        target_bits: int = 8,
        quantize_period: int = 1000,
        schedule_offset: int = 0,
    ):
        self.q_groups = int(q_groups)
        self.q_mixed_fp16 = bool(q_mixed_fp16)
        self.q_change_ratio = float(q_change_ratio)
        self.q_type = int(q_type)
        self.q_verbose = bool(q_verbose)
        self.start_bits = int(start_bits)
        self.target_bits = int(target_bits)
        self.period = max(1, int(quantize_period))
        self.schedule_offset = int(schedule_offset)
        self.quantize_real_ratio = 1.0
        self._last_bits: Optional[int] = None  # for switch-edge detection
        self.out_shardings = None  # engine sets this to the param shardings
        self._jit_cache: Dict[int, Any] = {}

    def state_dict(self) -> Dict[str, Any]:
        """The anneal ratio AND the last-seen bit-width are path-dependent
        state — without _last_bits, a resume whose first step lands exactly
        on a precision switch would miss the ratio-reset edge."""
        return {
            "quantize_real_ratio": self.quantize_real_ratio,
            "last_bits": self._last_bits,
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.quantize_real_ratio = float(sd.get("quantize_real_ratio", 1.0))
        last = sd.get("last_bits")
        self._last_bits = int(last) if last is not None else None

    def current_bits(self, step: int) -> int:
        """Bit-width at ``step``: drops by ONE bit per precision switch,
        with the switch threshold doubling each time (reference
        ``compute_quantization`` quantize.py:135 — ``start_bits -= 1``,
        ``q_period <<= 1``): switches land at period, 2*period, 4*period, …
        so 16→8 completes after 128×period steps."""
        if step < self.schedule_offset:
            return self.start_bits
        bits = self.start_bits
        threshold = self.period
        s = step - self.schedule_offset
        while bits > self.target_bits and s >= threshold:
            bits -= 1
            threshold *= 2
        return bits

    def update_ratio(self) -> float:
        """Anneal the fp16-mix ratio (reference ``update_fp16_ratio``)."""
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(0.0, self.quantize_real_ratio - self.q_change_ratio)
        else:
            self.quantize_real_ratio = 0.0
        return self.quantize_real_ratio

    def _build(self, bits: int):
        qfn = quantize_symmetric if self.q_type == 0 else quantize_asymmetric
        groups = self.q_groups

        def quantize_tree(params, ratio):
            def leaf(w):
                if not hasattr(w, "ndim") or w.ndim < 2 or not jnp.issubdtype(w.dtype, jnp.floating):  # lint: allow(DS-R003) — per-leaf structural dispatch, trace-time constant
                    return w
                if w.dtype == jnp.float32:  # lint: allow(DS-R003) — keep_fp32_params contract, trace-time constant
                    # keep_fp32_params leaves stay full precision in the
                    # mixed-precision compute tree — honor that request
                    return w
                qw = qfn(w, bits, groups)
                return (ratio * w + (1.0 - ratio) * qw).astype(w.dtype)

            return jax.tree_util.tree_map(leaf, params)

        # preserve the params' GSPMD layout: the per-group reshape would
        # otherwise let XLA pick a fresh output sharding and force a
        # reshard on the next step
        if self.out_shardings is not None:
            return jax.jit(quantize_tree, out_shardings=self.out_shardings)
        return jax.jit(quantize_tree)

    def quantize_tree(self, params, step: int):
        if step < self.schedule_offset:
            return params
        ratio = self.update_ratio()
        bits = self.current_bits(step)
        if self._last_bits is not None and bits < self._last_bits:
            # precision switch: the reference resets the blend to pure fp16
            # (quantize.py:137 ``quantize_real_ratio = 1.0``) so the mix
            # re-anneals after every drop
            self.quantize_real_ratio = 1.0
            ratio = 1.0
        self._last_bits = bits
        # the mixed-fp16 blend applies while bits >= target_bits - 1
        # (reference compute_quantization:170); with bits always >= target
        # that's every width — kept explicit for parity with the gate
        if not (self.q_mixed_fp16 and bits >= self.target_bits - 1):
            ratio = 0.0
        fn = self._jit_cache.get(bits)
        if fn is None:
            fn = self._jit_cache[bits] = self._build(bits)
        return fn(params, jnp.float32(ratio))


def moq_from_compression_config(compression_cfg: Optional[dict]) -> Optional[Quantizer]:
    """Build a Quantizer from the reference compression-config layout
    (``weight_quantization.shared_parameters`` with
    ``quantize_weight_in_forward: false`` — in-forward quantization is the
    QAT path owned by ``compression/``)."""
    if not compression_cfg:
        return None
    wq = compression_cfg.get("weight_quantization", {})
    shared = wq.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return None
    if shared.get("quantize_weight_in_forward", False):
        return None  # QAT (compression/) owns the in-forward path
    groups_cfg = wq.get("different_groups", {})
    if len(groups_cfg) > 1 or any("modules" in g for g in groups_cfg.values()):
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            "MoQ here applies ONE shared schedule to every weight: extra "
            "different_groups entries and per-group 'modules' patterns are "
            "ignored (the first group's bits/period win)"
        )
    start_bits, target_bits, period = 16, 8, 1000
    for g in groups_cfg.values():
        p = g.get("params", {})
        start_bits = int(p.get("start_bits", start_bits))
        target_bits = int(p.get("target_bits", target_bits))
        period = int(p.get("quantize_period", period))
        break  # shared schedule: the first group sets it
    return Quantizer(
        q_groups=int(shared.get("quantize_groups", 1)),
        q_mixed_fp16=bool(shared.get("fp16_mixed_quantize", {}).get("enabled", False))
        if isinstance(shared.get("fp16_mixed_quantize"), dict)
        else bool(shared.get("fp16_mixed_quantize", False)),
        q_change_ratio=float(
            shared.get("fp16_mixed_quantize", {}).get("quantize_change_ratio", 0.001)
            if isinstance(shared.get("fp16_mixed_quantize"), dict)
            else shared.get("quantize_change_ratio", 0.001)
        ),
        q_type=0 if str(shared.get("quantization_type", "symmetric")) == "symmetric" else 1,
        q_verbose=bool(shared.get("quantize_verbose", False)),
        start_bits=start_bits,
        target_bits=target_bits,
        quantize_period=period,
        schedule_offset=int(shared.get("schedule_offset", 0)),
    )
