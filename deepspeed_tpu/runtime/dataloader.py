"""Dataloader.

Counterpart of ``deepspeed/runtime/dataloader.py`` (``DeepSpeedDataLoader``
with ``DistributedSampler``). TPU-native behavior: batches are *global* —
the engine shards the leading dim over the dense-DP mesh axes at
``device_put`` time — so the sampler's job is only per-process slicing of the
global batch when running multi-host (each host loads its addressable slice).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Optional

import numpy as np


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: _default_collate([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([it[i] for it in items]) for i in range(len(first)))
    return np.stack([np.asarray(it) for it in items])


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference pipe utils)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Optional[Callable] = None,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        num_local_io_workers: Optional[int] = None,  # noqa: ARG002 - API parity
        data_sampler=None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.post_process_func = None
        self.data_sampler = data_sampler
        self.epoch = 0
        try:
            self._len = len(dataset)
        except TypeError:
            self._len = None

    def __len__(self) -> int:
        if self._len is None:
            raise TypeError("dataset has no length")
        if self.drop_last:
            return self._len // self.batch_size
        return math.ceil(self._len / self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _indices(self):
        n = self._len
        order = np.arange(n)
        if self.data_sampler is not None:
            order = np.asarray(list(iter(self.data_sampler)))
        elif self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        return order

    def __iter__(self):
        if self._len is None:
            # iterable dataset: batch on the fly
            for batch in self._iter_stream():
                yield self._post(batch)
            return
        order = self._indices()
        n_batches = len(self)
        for b in range(n_batches):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            items = [self.dataset[int(i)] for i in idx]
            yield self._post(self.collate_fn(items))

    def _post(self, batch):
        """Data-efficiency hook (reference engine.set_data_post_process_func
        -> dataloader.post_process_func): applied to each emitted batch."""
        return self.post_process_func(batch) if self.post_process_func else batch

    def _iter_stream(self):
        buf = []
        for item in self.dataset:
            buf.append(item)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.collate_fn(buf)
