"""Dataloader.

Counterpart of ``deepspeed/runtime/dataloader.py`` (``DeepSpeedDataLoader``
with ``DistributedSampler``). TPU-native behavior: batches are *global* —
the engine shards the leading dim over the dense-DP mesh axes at
``device_put`` time — so the sampler's job is only per-process slicing of the
global batch when running multi-host (each host loads its addressable slice).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Optional

import numpy as np


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: _default_collate([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([it[i] for it in items]) for i in range(len(first)))
    return np.stack([np.asarray(it) for it in items])


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference pipe utils).

    Carries a resumable cursor for exact-resume checkpointing: when the
    wrapped loader exposes ``state_dict``/``load_state_dict`` (as
    ``DeepSpeedDataLoader`` does) the inner cursor is delegated to;
    otherwise the served-batch count is recorded and replayed best-effort."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self.batches_served = 0

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        self.batches_served += 1
        return batch

    def state_dict(self):
        sd = {"batches_served": self.batches_served}
        if hasattr(self.loader, "state_dict"):
            sd["loader"] = self.loader.state_dict()
        return sd

    def load_state_dict(self, sd) -> None:
        self.batches_served = int(sd.get("batches_served", 0))
        if "loader" in sd and hasattr(self.loader, "load_state_dict"):
            self.loader.load_state_dict(sd["loader"])
            self.data_iter = iter(self.loader)
            return
        # opaque inner iterable: replay from the start (deterministic
        # loaders land on the same cursor; anything else cannot be resumed
        # exactly and should expose state_dict itself). Replay restarts on
        # exhaustion exactly like __next__ — batches_served is cumulative
        # across wraparounds, so an unsized loader replays whole passes.
        self.data_iter = iter(self.loader)
        try:
            n = len(self.loader)
        except TypeError:
            n = 0
        for _ in range(self.batches_served % n if n else self.batches_served):
            try:
                next(self.data_iter)
            except StopIteration:
                self.data_iter = iter(self.loader)
                next(self.data_iter)


class DeepSpeedDataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Optional[Callable] = None,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        num_local_io_workers: Optional[int] = None,  # noqa: ARG002 - API parity
        data_sampler=None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.post_process_func = None
        self.data_sampler = data_sampler
        self.epoch = 0
        # resumable data cursor (exact-resume checkpointing): batches
        # yielded in the current epoch, saved via state_dict and consumed
        # ONCE by the next __iter__ after load_state_dict
        self._cursor = 0
        self._resume_cursor = 0
        try:
            self._len = len(dataset)
        except TypeError:
            self._len = None

    def __len__(self) -> int:
        if self._len is None:
            raise TypeError("dataset has no length")
        if self.drop_last:
            return self._len // self.batch_size
        return math.ceil(self._len / self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        """Select the epoch; cursors reset only when it actually CHANGES.
        The canonical resumed loop calls ``set_epoch(current_epoch)`` right
        after ``load_checkpoint`` — that must not wipe the restored
        mid-epoch cursor, or the resumed run silently re-serves already
        trained batches."""
        if epoch != self.epoch:
            self._cursor = 0
            self._resume_cursor = 0
        self.epoch = epoch

    def state_dict(self) -> dict:
        """The data cursor: where in which epoch the loader stands. Saved
        into checkpoints so an ``auto_resume`` run replays the EXACT batch
        sequence an uninterrupted run would have seen."""
        return {"epoch": self.epoch, "cursor": self._cursor}

    def load_state_dict(self, sd: dict) -> None:
        self.epoch = int(sd.get("epoch", 0))
        self._cursor = int(sd.get("cursor", 0))
        self._resume_cursor = self._cursor

    def _indices(self):
        n = self._len
        order = np.arange(n)
        if self.data_sampler is not None:
            order = np.asarray(list(iter(self.data_sampler)))
        elif self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        return order

    def __iter__(self):
        start, self._resume_cursor = self._resume_cursor, 0
        if self._len is None:
            # iterable dataset: batch on the fly (resume = deterministic
            # replay past the already-consumed batches)
            for b, batch in enumerate(self._iter_stream()):
                if b < start:
                    continue
                self._cursor = b + 1
                yield self._post(batch)
            self.epoch += 1
            self._cursor = 0
            return
        order = self._indices()
        n_batches = len(self)
        for b in range(min(start, n_batches), n_batches):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            items = [self.dataset[int(i)] for i in idx]
            self._cursor = b + 1
            yield self._post(self.collate_fn(items))
        # a completed pass rolls the cursor into the next epoch, so a
        # RepeatingLoader's wraparound is captured in the saved state
        self.epoch += 1
        self._cursor = 0

    def _post(self, batch):
        """Data-efficiency hook (reference engine.set_data_post_process_func
        -> dataloader.post_process_func): applied to each emitted batch."""
        return self.post_process_func(batch) if self.post_process_func else batch

    def _iter_stream(self):
        buf = []
        for item in self.dataset:
            buf.append(item)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.collate_fn(buf)
