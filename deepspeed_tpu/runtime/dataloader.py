"""Dataloader.

Counterpart of ``deepspeed/runtime/dataloader.py`` (``DeepSpeedDataLoader``
with ``DistributedSampler``). TPU-native behavior: batches are *global* —
the engine shards the leading dim over the dense-DP mesh axes at
``device_put`` time — so the sampler's job is only per-process slicing of the
global batch when running multi-host (each host loads its addressable slice).
"""

from __future__ import annotations

import copy
import math
from collections import deque
from typing import Any, Callable, Iterable, Optional

import numpy as np


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: _default_collate([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([it[i] for it in items]) for i in range(len(first)))
    return np.stack([np.asarray(it) for it in items])


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference pipe utils).

    Carries a resumable cursor for exact-resume checkpointing: when the
    wrapped loader exposes ``state_dict``/``load_state_dict`` (as
    ``DeepSpeedDataLoader`` does) the inner cursor is delegated to;
    otherwise the served-batch count is recorded and replayed best-effort."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self.batches_served = 0

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        self.batches_served += 1
        return batch

    def state_dict(self):
        sd = {"batches_served": self.batches_served}
        if hasattr(self.loader, "state_dict"):
            sd["loader"] = self.loader.state_dict()
        return sd

    def load_state_dict(self, sd) -> None:
        self.batches_served = int(sd.get("batches_served", 0))
        if "loader" in sd and hasattr(self.loader, "load_state_dict"):
            self.loader.load_state_dict(sd["loader"])
            self.data_iter = iter(self.loader)
            return
        # opaque inner iterable: replay from the start (deterministic
        # loaders land on the same cursor; anything else cannot be resumed
        # exactly and should expose state_dict itself). Replay restarts on
        # exhaustion exactly like __next__ — batches_served is cumulative
        # across wraparounds, so an unsized loader replays whole passes.
        self.data_iter = iter(self.loader)
        try:
            n = len(self.loader)
        except TypeError:
            n = 0
        for _ in range(self.batches_served % n if n else self.batches_served):
            try:
                next(self.data_iter)
            except StopIteration:
                self.data_iter = iter(self.loader)
                next(self.data_iter)


class PrefetchingLoader:
    """Double-buffered input pipeline (ISSUE 14).

    Wraps a batch source (an iterator, or an iterable like
    :class:`DeepSpeedDataLoader`) and keeps up to ``depth`` batches pulled
    ahead, applying ``place_fn`` — typically the engine's sharded
    ``device_put`` (``engine._place_batch``) — at PULL time. The host→device
    transfer of batch i+1 is therefore enqueued while step/window i is still
    computing on device, taking ``train.data_fetch`` + ``train.h2d`` off the
    step's critical path.

    Exact-resume contract (PR-8 mid-epoch resume must keep holding):
    pulling ahead advances the underlying loader's cursor past batches that
    have NOT been trained yet, so ``state_dict()`` here reports the cursor
    of the first *undelivered* batch — a snapshot of ``state_source``
    (default: the wrapped source, when it exposes ``state_dict``) taken
    immediately before each pull. A checkpoint cut mid-prefetch thus
    replays the buffered-but-untrained batches on resume instead of
    skipping them. ``load_state_dict`` drops the stale buffer, restores the
    source cursor, and re-iterates the source — it therefore requires a
    RE-ITERABLE source (wrap the loader itself); over a bare iterator it
    raises, because a running generator cannot rewind (rebuild the wrapper
    after restoring the loader's cursor instead, as the engine does).
    """

    def __init__(self, source, place_fn: Optional[Callable] = None, depth: int = 1, state_source=None):
        self._source = source
        self._iter = iter(source)
        self.place_fn = place_fn
        self.depth = max(int(depth), 0)
        if state_source is None and hasattr(source, "state_dict"):
            state_source = source
        self._state_source = state_source
        self._buf: deque = deque()  # (placed_batch, cursor_snapshot_before_pull)
        self._exhausted = False

    def _snap(self):
        if self._state_source is None:
            return None
        return copy.deepcopy(self._state_source.state_dict())

    def _pull(self) -> bool:
        """Stage one more batch (snapshot cursor, fetch, place). False once
        the source is exhausted — StopIteration is latched so a generator
        source is never advanced past its end twice."""
        if self._exhausted:
            return False
        snap = self._snap()
        try:
            batch = next(self._iter)
        except StopIteration:
            self._exhausted = True
            return False
        if self.place_fn is not None:
            batch = self.place_fn(batch)
        self._buf.append((batch, snap))
        return True

    def fill(self, n: Optional[int] = None) -> int:
        """Pull until ``n`` (default: ``depth``) batches are buffered or the
        source runs dry; returns the buffered count. The window former uses
        this to ask 'does a full window of data exist?' without consuming."""
        target = self.depth if n is None else int(n)
        while len(self._buf) < target and self._pull():
            pass
        return len(self._buf)

    def buffered(self) -> int:
        return len(self._buf)

    def __iter__(self):
        return self

    def __next__(self):
        if not self._buf and not self._pull():
            raise StopIteration
        batch, _ = self._buf.popleft()
        # top back up: this is the double buffer — the NEXT batch's
        # device_put is enqueued now, while the consumer's current
        # step/window still owns the device
        self.fill(self.depth)
        return batch

    def state_dict(self) -> Optional[dict]:
        """Cursor of the first undelivered batch (see class docstring)."""
        if self._buf:
            snap = self._buf[0][1]
            return copy.deepcopy(snap) if snap is not None else None
        return self._snap()

    def load_state_dict(self, sd) -> None:
        if iter(self._source) is self._source:
            # a running iterator/generator cannot rewind: "restoring" it
            # would silently skip every staged-but-untrained batch — the
            # exact sample loss this class exists to prevent. Only a
            # RE-ITERABLE source (the loader itself) can resume in place;
            # iterator-wrapped pipelines rebuild the wrapper after
            # restoring the loader's own cursor (what the engine does).
            raise ValueError(
                "PrefetchingLoader.load_state_dict requires a re-iterable "
                "source (wrap the loader, not iter(loader)): a bare "
                "iterator cannot rewind to the restored cursor; restore "
                "the loader's cursor and rebuild the wrapper instead"
            )
        self._buf.clear()
        self._exhausted = False
        if self._state_source is not None and sd is not None:
            self._state_source.load_state_dict(sd)
        self._iter = iter(self._source)


class DeepSpeedDataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Optional[Callable] = None,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        num_local_io_workers: Optional[int] = None,  # noqa: ARG002 - API parity
        data_sampler=None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.post_process_func = None
        self.data_sampler = data_sampler
        self.epoch = 0
        # resumable data cursor (exact-resume checkpointing): batches
        # yielded in the current epoch, saved via state_dict and consumed
        # ONCE by the next __iter__ after load_state_dict
        self._cursor = 0
        self._resume_cursor = 0
        try:
            self._len = len(dataset)
        except TypeError:
            self._len = None

    def __len__(self) -> int:
        if self._len is None:
            raise TypeError("dataset has no length")
        if self.drop_last:
            return self._len // self.batch_size
        return math.ceil(self._len / self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        """Select the epoch; cursors reset only when it actually CHANGES.
        The canonical resumed loop calls ``set_epoch(current_epoch)`` right
        after ``load_checkpoint`` — that must not wipe the restored
        mid-epoch cursor, or the resumed run silently re-serves already
        trained batches."""
        if epoch != self.epoch:
            self._cursor = 0
            self._resume_cursor = 0
        self.epoch = epoch

    def state_dict(self) -> dict:
        """The data cursor: where in which epoch the loader stands. Saved
        into checkpoints so an ``auto_resume`` run replays the EXACT batch
        sequence an uninterrupted run would have seen."""
        return {"epoch": self.epoch, "cursor": self._cursor}

    def load_state_dict(self, sd: dict) -> None:
        self.epoch = int(sd.get("epoch", 0))
        self._cursor = int(sd.get("cursor", 0))
        self._resume_cursor = self._cursor

    def _indices(self):
        n = self._len
        order = np.arange(n)
        if self.data_sampler is not None:
            order = np.asarray(list(iter(self.data_sampler)))
        elif self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        return order

    def __iter__(self):
        start, self._resume_cursor = self._resume_cursor, 0
        if self._len is None:
            # iterable dataset: batch on the fly (resume = deterministic
            # replay past the already-consumed batches)
            for b, batch in enumerate(self._iter_stream()):
                if b < start:
                    continue
                self._cursor = b + 1
                yield self._post(batch)
            self.epoch += 1
            self._cursor = 0
            return
        order = self._indices()
        n_batches = len(self)
        for b in range(min(start, n_batches), n_batches):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            items = [self.dataset[int(i)] for i in idx]
            self._cursor = b + 1
            yield self._post(self.collate_fn(items))
        # a completed pass rolls the cursor into the next epoch, so a
        # RepeatingLoader's wraparound is captured in the saved state
        self.epoch += 1
        self._cursor = 0

    def _post(self, batch):
        """Data-efficiency hook (reference engine.set_data_post_process_func
        -> dataloader.post_process_func): applied to each emitted batch."""
        return self.post_process_func(batch) if self.post_process_func else batch

    def _iter_stream(self):
        buf = []
        for item in self.dataset:
            buf.append(item)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.collate_fn(buf)
