"""ZeRO-Infinity parameter offload: layer-streamed training.

TPU-native counterpart of the reference's partitioned-parameter offload
(``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:36`` NVMe param
partitions, ``deepspeed/runtime/zero/stage3.py:542`` ``_configure_tensor_swapping``,
prefetch at ``partitioned_param_coordinator.py:503``). The reference keeps
torch params as empty shells and swaps flat partitions in before each
submodule's hook fires; under XLA a jitted program needs its params resident,
so the TPU design restructures the step instead:

* the model's stacked decoder layers live OFF-chip — compute-dtype trees in
  host DRAM (``offload_param.device=cpu``) or on local SSD via the native AIO
  library (``device=nvme``), one file per layer, double-buffer prefetched;
* the forward runs one jitted layer program per layer, ``device_put``-ing
  layer ``i+1`` (async, overlapped with compute) while layer ``i`` runs —
  the coordinator's prefetch window, with XLA's transfer queue as the engine;
* the backward re-runs each layer under ``jax.vjp`` (activation remat),
  streams the layer gradient back to the host, and accumulates it in fp32;
* the optimizer never touches the chip: fp32 master + Adam moments stay in
  host DRAM and update through the native AVX Adam
  (``csrc/adam/cpu_adam.cpp``), then the new compute-dtype layer params are
  written back to the store (DRAM or SSD).

Device HBM therefore holds: the resident (non-layer) params, TWO layers'
worth of streamed params, the activation stash (optionally host-offloaded,
``cpu_checkpointing``), and transient layer compute — so trainable model
size is bounded by host DRAM/SSD, not HBM: the ZeRO-Infinity scaling claim.

Works with any model family exposing ``stream_fns()`` (embed/layer/head
programs + stacked layer params) — the built-in dense ``TransformerLM`` does;
MoE families raise (expert params live outside the stacked layer tree).
"""

from __future__ import annotations

import math
import os
import tempfile
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam_native import (
    NativeCPUAdam,
    native_adam_available,
)
from deepspeed_tpu.utils.logging import log_dist


def _np_dtype(jax_dtype):
    return np.dtype(jnp.dtype(jax_dtype).name)


class LayerParamStore:
    """Per-layer compute-dtype param trees in host DRAM or on NVMe.

    NVMe mode packs each layer's leaves into one contiguous buffer written to
    ``<dir>/layer_<i>.bin`` (the reference's flat swap files,
    ``partitioned_param_swapper.py``), with ``buffer_count`` host staging
    buffers and one AIO handle per buffer so reads for layer ``i+1`` overlap
    the device compute of layer ``i``.
    """

    def __init__(self, layers_host: List[Dict[str, np.ndarray]], device: str,
                 nvme_dir: Optional[str] = None, buffer_count: int = 2):
        self.n_layers = len(layers_host)
        self.device = device
        leaves0, self._treedef = jax.tree_util.tree_flatten(layers_host[0])
        self._shapes = [l.shape for l in leaves0]
        self._dtypes = [l.dtype for l in leaves0]
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self._nbytes = [s * d.itemsize for s, d in zip(self._sizes, self._dtypes)]
        self._offsets = np.cumsum([0] + self._nbytes).tolist()
        self.layer_nbytes = self._offsets[-1]

        if device == "nvme":
            from deepspeed_tpu.ops.aio import AsyncIOHandle

            self._dir = nvme_dir or os.path.join(tempfile.gettempdir(), "ds_tpu_param_swap")
            os.makedirs(self._dir, exist_ok=True)
            n_buf = max(2, buffer_count)
            self._read_handles = [AsyncIOHandle() for _ in range(n_buf)]
            self._write_handle = AsyncIOHandle()
            self._staging = [np.empty(self.layer_nbytes, np.uint8) for _ in range(n_buf)]
            self._staged_layer = [-1] * n_buf  # layer currently in each buffer
            self._pending = [False] * n_buf  # read in flight
            self._write_bufs: List[np.ndarray] = []
            for i, tree in enumerate(layers_host):
                self._write_handle.sync_pwrite(self._pack(tree), self._file(i))
            self._dram = None
        else:
            self._dram = [
                jax.tree_util.tree_map(np.ascontiguousarray, t) for t in layers_host
            ]

    def _file(self, i: int) -> str:
        return os.path.join(self._dir, f"layer_{i}.bin")

    def _pack(self, tree) -> np.ndarray:
        buf = np.empty(self.layer_nbytes, np.uint8)
        for leaf, off, nb in zip(
            jax.tree_util.tree_leaves(tree), self._offsets, self._nbytes
        ):
            buf[off : off + nb] = np.ascontiguousarray(leaf).view(np.uint8).ravel()
        return buf

    def _unpack(self, buf: np.ndarray):
        leaves = [
            buf[off : off + nb].view(dt).reshape(shape)
            for off, nb, dt, shape in zip(
                self._offsets, self._nbytes, self._dtypes, self._shapes
            )
        ]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def _buf_slot(self, i: int) -> int:
        return i % len(self._staging)

    def start_fetch(self, i: int) -> None:
        """Begin moving layer ``i`` toward host staging (async disk read)."""
        if self._dram is not None or not (0 <= i < self.n_layers):
            return
        slot = self._buf_slot(i)
        if self._staged_layer[slot] == i:
            return
        if self._pending[slot]:
            self._read_handles[slot].wait()
            self._pending[slot] = False
        self._read_handles[slot].async_pread(self._staging[slot], self._file(i))
        self._staged_layer[slot] = i
        self._pending[slot] = True

    def get_layer(self, i: int):
        """Host tree of layer ``i`` (blocks on any pending read).

        NVMe mode returns views into an OWNED copy of the staged bytes, not
        the staging buffer itself: ``jax.device_put`` may alias host memory
        (zero-copy on the cpu backend) and the slot is overwritten by a later
        prefetch — handing out live staging views corrupts in-flight layers
        whenever ``n_layers > buffer_count``."""
        if self._dram is not None:
            return self._dram[i]
        slot = self._buf_slot(i)
        if self._staged_layer[slot] != i:
            self.start_fetch(i)
        if self._pending[slot]:
            self._read_handles[slot].wait()
            self._pending[slot] = False
        return self._unpack(self._staging[slot].copy())

    def update_layer(self, i: int, new_tree) -> None:
        """Write back an updated layer (async on NVMe; caller flush()es)."""
        if self._dram is not None:
            for dst, src in zip(
                jax.tree_util.tree_leaves(self._dram[i]),
                jax.tree_util.tree_leaves(new_tree),
            ):
                np.copyto(dst, np.asarray(src).astype(dst.dtype))
            return
        buf = self._pack(new_tree)
        self._write_bufs.append(buf)  # keep alive until flush
        self._write_handle.async_pwrite(buf, self._file(i))
        slot = self._buf_slot(i)
        if self._staged_layer[slot] == i:
            self._staged_layer[slot] = -1  # staged copy is stale now

    def flush(self) -> None:
        if self._dram is None:
            self._write_handle.wait()
            self._write_bufs.clear()


class _HostLeafState:
    """fp32 master + Adam moments for the flattened leaves of one layer.

    Moments allocate lazily at the first optimizer step so inference-only
    engines never pay the 2× fp32 host cost."""

    __slots__ = ("master", "exp_avg", "exp_avg_sq")

    def __init__(self, flat_master: np.ndarray):
        self.master = flat_master
        self.exp_avg: Optional[np.ndarray] = None
        self.exp_avg_sq: Optional[np.ndarray] = None

    def ensure_moments(self) -> None:
        if self.exp_avg is None:
            self.exp_avg = np.zeros_like(self.master)
            self.exp_avg_sq = np.zeros_like(self.master)


class ParamStreamEngine:
    """Forward/backward/step over a layer store (see module docstring)."""

    def __init__(
        self,
        module,
        params,  # fully materialized compute-dtype tree (init-time; released)
        topology,
        zero_config,
        optimizer_params: Dict[str, Any],
        compute_dtype,
        fp16: bool = False,
        act_offload: bool = False,
    ):
        if not hasattr(module, "stream_fns"):
            raise ValueError(
                "offload_param needs a layer-streamable model: the module must "
                "expose stream_fns() (built-in transformer families do); got "
                f"{type(module).__name__}"
            )
        self.module = module
        self.topology = topology
        self.mesh = topology.mesh
        self.compute_dtype = compute_dtype
        self.fp16 = fp16
        self.act_offload = act_offload
        off = zero_config.offload_param
        self.embed_fwd, self.layer_fwd, self.head_loss = module.stream_fns()

        # --- split params: resident (embed/head/norm) vs streamed layers ---
        layers_stacked = params["layers"]
        self.n_layers = int(jax.tree_util.tree_leaves(layers_stacked)[0].shape[0])
        resident = {k: v for k, v in params.items() if k != "layers"}

        from jax.sharding import NamedSharding, PartitionSpec

        self._replicated = NamedSharding(self.mesh, PartitionSpec())
        self.resident = jax.device_put(
            jax.tree_util.tree_map(lambda x: jnp.asarray(x, compute_dtype), resident),
            self._replicated,
        )

        # host per-layer compute-dtype trees + fp32 master/moment state
        layers_host: List[Dict[str, np.ndarray]] = []
        self._layer_state: List[_HostLeafState] = []
        for i in range(self.n_layers):
            tree = jax.tree_util.tree_map(lambda x: np.asarray(x[i]), layers_stacked)
            flat = np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in jax.tree_util.tree_leaves(tree)]
            )
            self._layer_state.append(_HostLeafState(flat))
            layers_host.append(
                jax.tree_util.tree_map(
                    lambda x: np.asarray(x).astype(_np_dtype(compute_dtype)), tree
                )
            )
        self._resident_state = _HostLeafState(
            np.concatenate(
                [
                    np.asarray(jax.device_get(l), np.float32).ravel()
                    for l in jax.tree_util.tree_leaves(self.resident)
                ]
            )
            if jax.tree_util.tree_leaves(self.resident)
            else np.zeros(0, np.float32)
        )

        self.store = LayerParamStore(
            layers_host,
            device=str(getattr(off, "device", "cpu")).split(".")[-1],
            nvme_dir=(
                os.path.join(str(off.nvme_path), "ds_tpu_param_swap")
                if getattr(off, "nvme_path", None)
                else None
            ),
            buffer_count=int(getattr(off, "buffer_count", 2) or 2),
        )

        # the native optimizer builds lazily at the first step() so
        # inference-only use neither requires the cpu_adam build nor pays
        # for moment allocation
        self._optimizer_params = dict(optimizer_params)
        self._adam: Optional[NativeCPUAdam] = None
        self.step_count = 0

        # host fp32 grad accumulators (layer-major, + resident)
        self._grad_acc = [np.zeros_like(s.master) for s in self._layer_state]
        self._grad_acc_res = np.zeros_like(self._resident_state.master)
        self._micro_in_window = 0

        # activation stash from the last forward
        self._acts: List[Any] = []
        self._stash = None

        self._jit_cache: Dict[str, Any] = {}
        n_host = sum(s.master.nbytes * 3 for s in self._layer_state)
        log_dist(
            f"ParamStreamEngine: {self.n_layers} streamed layers, "
            f"{self.store.layer_nbytes / 1024**2:.1f} MB/layer on "
            f"{self.store.device}, {n_host / 1024**2:.1f} MB host optimizer state",
            ranks=[0],
        )

    @property
    def adam(self) -> NativeCPUAdam:
        if self._adam is None:
            if not native_adam_available():
                raise RuntimeError(
                    "offload_param training requires the native cpu_adam op "
                    "(g++ build failed?)"
                )
            self._adam = NativeCPUAdam(
                betas=tuple(self._optimizer_params.get("betas", (0.9, 0.999))),
                eps=self._optimizer_params.get("eps", 1e-8),
                weight_decay=self._optimizer_params.get("weight_decay", 0.0),
                adamw_mode=self._optimizer_params.get("adam_w_mode", True),
            )
        return self._adam

    # ------------------------------------------------------------------
    # jitted programs (built lazily, cached by shape via jax.jit)
    # ------------------------------------------------------------------
    def _programs(self):
        if self._jit_cache:
            return self._jit_cache
        embed_fwd, layer_fwd, head_loss = self.embed_fwd, self.layer_fwd, self.head_loss
        repl = self._replicated

        def j_embed(resident, tokens):
            return embed_fwd(resident, tokens)

        def j_layer(layer_p, h, positions, rng):
            return layer_fwd(layer_p, h, positions, rng)

        def j_layer_eval(layer_p, h, positions):
            return layer_fwd(layer_p, h, positions, None, train=False)

        def j_head(resident, h, labels, scale):
            return head_loss(resident, h, labels) * scale

        def j_head_eval(resident, h, labels):
            # labels=None → the model's head returns logits (inference)
            return head_loss(resident, h, labels)

        def j_head_bwd(resident, h, labels, scale):
            (loss), vjp = jax.vjp(lambda r, x: head_loss(r, x, labels) * scale, resident, h)
            g_res, g_h = vjp(jnp.ones((), jnp.float32))
            return loss, g_h, g_res

        def j_layer_bwd(layer_p, h_in, positions, rng, g_out):
            _, vjp = jax.vjp(lambda p, x: layer_fwd(p, x, positions, rng), layer_p, h_in)
            g_p, g_h = vjp(g_out)
            return g_h, g_p

        def j_embed_bwd(resident, tokens, g_h):
            _, vjp = jax.vjp(lambda r: embed_fwd(r, tokens), resident)
            (g_res,) = vjp(g_h)
            return g_res

        # replicated grad out-shardings make XLA insert the data-axis psum
        # (the reference's reduce-scatter/allreduce of stage3 grads)
        self._jit_cache = {
            "embed": jax.jit(j_embed),
            "layer": jax.jit(j_layer, out_shardings=None),
            "layer_eval": jax.jit(j_layer_eval),
            "head": jax.jit(j_head),
            "head_eval": jax.jit(j_head_eval),
            "head_bwd": jax.jit(j_head_bwd, out_shardings=(None, None, repl)),
            "layer_bwd": jax.jit(j_layer_bwd, out_shardings=(None, repl)),
            "embed_bwd": jax.jit(j_embed_bwd, out_shardings=repl),
        }
        return self._jit_cache

    def _put_layer(self, i: int):
        """Host tree → device (replicated), async."""
        return jax.device_put(self.store.get_layer(i), self._replicated)

    # ------------------------------------------------------------------
    # forward / backward / step
    # ------------------------------------------------------------------
    def _stream_layers(self, h, positions, rng, train: bool, stash: bool):
        """The double-buffered layer stream: prefetch layer ``i+1`` (disk →
        host staging AND host → device) while layer ``i`` computes."""
        progs = self._programs()
        self.store.start_fetch(0)
        dev_next = self._put_layer(0) if self.n_layers else None
        for i in range(self.n_layers):
            self.store.start_fetch(i + 1)
            dev_i, dev_next = dev_next, None
            if stash:
                self._stash_act(h)
            if train:
                h_out = progs["layer"](dev_i, h, positions, jax.random.fold_in(rng, i))
            else:
                h_out = progs["layer_eval"](dev_i, h, positions)
            if i + 1 < self.n_layers:
                dev_next = self._put_layer(i + 1)  # overlaps layer i compute
            h = h_out
            del dev_i
        return h

    def forward(self, tokens, labels, rng, scale: float):
        progs = self._programs()
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :], tokens.shape
        )
        h = progs["embed"](self.resident, tokens)
        self._acts = []
        h = self._stream_layers(h, positions, rng, train=True, stash=True)
        loss = progs["head"](self.resident, h, labels, jnp.float32(scale))
        self._stash = (tokens, labels, positions, rng, h)
        return loss

    def eval_forward(self, tokens, labels=None):
        """Deterministic forward (train=False programs, no activation stash,
        no loss scaling) — the stream-path analog of the engine's
        ``_jit_eval``. With ``labels=None`` the head returns logits
        (inference); otherwise the eval loss."""
        progs = self._programs()
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :], tokens.shape
        )
        h = progs["embed"](self.resident, tokens)
        h = self._stream_layers(h, positions, None, train=False, stash=False)
        return progs["head_eval"](self.resident, h, labels)

    def _stash_act(self, h):
        if self.act_offload:
            self._acts.append(np.asarray(jax.device_get(h)))
        else:
            self._acts.append(h)

    def _fetch_act(self, i):
        h = self._acts[i]
        if self.act_offload:
            return jax.device_put(h)
        return h

    def backward(self, scale: float):
        """Stream the backward; accumulate fp32 grads on host."""
        progs = self._programs()
        tokens, labels, positions, rng, h_last = self._stash
        _, g_h, g_res = progs["head_bwd"](
            self.resident, h_last, labels, jnp.float32(scale)
        )
        res_acc = np.zeros_like(self._grad_acc_res)
        _accumulate_flat(res_acc, g_res)
        # prefetch from the top of the stack downward
        self.store.start_fetch(self.n_layers - 1)
        dev_next = self._put_layer(self.n_layers - 1) if self.n_layers else None
        for i in range(self.n_layers - 1, -1, -1):
            self.store.start_fetch(i - 1)
            dev_i, dev_next = dev_next, None
            h_in = self._fetch_act(i)
            g_h, g_p = progs["layer_bwd"](
                dev_i, h_in, positions, jax.random.fold_in(rng, i), g_h
            )
            if i - 1 >= 0:
                dev_next = self._put_layer(i - 1)
            _accumulate_flat(self._grad_acc[i], g_p)
            del dev_i
        g_res_emb = progs["embed_bwd"](self.resident, tokens, g_h)
        _accumulate_flat(res_acc, g_res_emb)
        self._grad_acc_res += res_acc
        self._micro_in_window += 1
        self._acts = []
        self._stash = None

    def step(self, lr: float, scale: float, clip: float):
        """Host optimizer pass over every layer + the resident params.

        Returns (grad_norm, overflow). Grads are unscaled by
        ``1/(scale*micro_steps)``; on fp16 overflow the update is skipped
        entirely (reference overflow-skip semantics)."""
        inv = 1.0 / (scale * max(self._micro_in_window, 1))
        sq = 0.0
        finite = True
        for acc in self._grad_acc + [self._grad_acc_res]:
            a = acc * inv
            s = float(np.dot(a, a))
            if not math.isfinite(s):
                finite = False
                break
            sq += s
        overflow = self.fp16 and not finite
        grad_norm = math.sqrt(sq) if finite else float("nan")
        if not overflow:
            coef = inv * (min(1.0, clip / (grad_norm + 1e-6)) if clip > 0 else 1.0)
            self.step_count += 1
            for i in range(self.n_layers):
                st = self._layer_state[i]
                st.ensure_moments()
                g = self._grad_acc[i] * coef
                self.adam.step(st.master, g, st.exp_avg, st.exp_avg_sq,
                               step=self.step_count, lr=lr)
                self.store.update_layer(
                    i, self._unflatten_layer(st.master.astype(_np_dtype(self.compute_dtype)))
                )
            if self._resident_state.master.size:
                st = self._resident_state
                st.ensure_moments()
                g = self._grad_acc_res * coef
                self.adam.step(st.master, g, st.exp_avg, st.exp_avg_sq,
                               step=self.step_count, lr=lr)
                self.resident = jax.device_put(
                    _unflatten_like(self.resident, st.master, self.compute_dtype),
                    self._replicated,
                )
            self.store.flush()
        for acc in self._grad_acc:
            acc[:] = 0.0
        self._grad_acc_res[:] = 0.0
        self._micro_in_window = 0
        return grad_norm, overflow

    def _unflatten_layer(self, flat: np.ndarray):
        tpl = self.store
        leaves, off = [], 0
        for shape, size in zip(tpl._shapes, tpl._sizes):
            leaves.append(flat[off : off + size].reshape(shape))
            off += size
        return jax.tree_util.tree_unflatten(tpl._treedef, leaves)

    # ------------------------------------------------------------------
    # introspection / checkpoint
    # ------------------------------------------------------------------
    def params_treedef(self):
        """Tree structure of ``gathered_params()`` with no layer copies."""
        out = dict(self.resident)
        out["layers"] = jax.tree_util.tree_unflatten(
            self.store._treedef, [0] * len(self.store._shapes)
        )
        return jax.tree_util.tree_structure(out)

    def gathered_params(self):
        """Full compute-dtype param tree (host-backed stacked layers).

        Copies each layer out immediately: on the NVMe store ``get_layer``
        returns views into staging buffers that later fetches reuse."""
        per_layer = [
            jax.tree_util.tree_map(np.array, self.store.get_layer(i))
            for i in range(self.n_layers)
        ]
        stacked = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *per_layer)
        out = dict(jax.tree_util.tree_map(np.asarray, jax.device_get(self.resident)))
        out["layers"] = stacked
        return out

    def master_params(self):
        """Full fp32 master tree (host-backed)."""
        per_layer = [
            self._unflatten_layer(st.master) for st in self._layer_state
        ]
        stacked = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *per_layer)
        out = _unflatten_like(self.resident, self._resident_state.master, jnp.float32)
        out = jax.tree_util.tree_map(np.asarray, out)
        out["layers"] = stacked
        return out

    def num_parameters(self) -> int:
        n = sum(st.master.size for st in self._layer_state)
        return n + self._resident_state.master.size

    @staticmethod
    def _leaf_state_dict(st: _HostLeafState) -> Dict[str, np.ndarray]:
        st.ensure_moments()
        return {
            "master": st.master.copy(),
            "exp_avg": st.exp_avg.copy(),
            "exp_avg_sq": st.exp_avg_sq.copy(),
        }

    def state_dict(self) -> Dict[str, Any]:
        return {
            "step": self.step_count,
            "layers": [self._leaf_state_dict(st) for st in self._layer_state],
            "resident": self._leaf_state_dict(self._resident_state),
        }

    def debug_grads(self):
        """Host fp32 grad accumulators as a param-shaped tree (the
        ``safe_get_full_grad`` surface). Values are the raw scaled
        accumulation of the current window (scale × Σ microbatches);
        ``None`` when the window is empty (e.g. right after ``step()``)."""
        if self._micro_in_window == 0:
            return None
        per_layer = [self._unflatten_layer(acc) for acc in self._grad_acc]
        stacked = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *per_layer)
        out = _unflatten_like(self.resident, self._grad_acc_res, jnp.float32)
        out = jax.tree_util.tree_map(np.asarray, out)
        out["layers"] = stacked
        return out

    def load_master_state(self, state: Dict[str, Any]) -> None:
        """Module-only load: adopt the checkpoint's fp32 masters (and refresh
        the compute store) with fresh moments and a reset step count."""
        for st, rec in zip(self._layer_state, state["layers"]):
            st.master[:] = np.asarray(rec["master"], np.float32)
            st.exp_avg = None
            st.exp_avg_sq = None
        self._resident_state.master[:] = np.asarray(state["resident"]["master"], np.float32)
        self._resident_state.exp_avg = None
        self._resident_state.exp_avg_sq = None
        self.step_count = 0
        self._materialize_from_master()

    @staticmethod
    def _load_leaf_state(st: _HostLeafState, rec: Dict[str, Any]) -> None:
        st.master[:] = np.asarray(rec["master"], np.float32)
        st.exp_avg = np.array(rec["exp_avg"], dtype=np.float32)
        st.exp_avg_sq = np.array(rec["exp_avg_sq"], dtype=np.float32)

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.step_count = int(state["step"])
        for st, rec in zip(self._layer_state, state["layers"]):
            self._load_leaf_state(st, rec)
        self._load_leaf_state(self._resident_state, state["resident"])
        self._materialize_from_master()

    def _materialize_from_master(self) -> None:
        """Refresh the compute-dtype store + resident params from master."""
        for i, st in enumerate(self._layer_state):
            self.store.update_layer(
                i, self._unflatten_layer(st.master.astype(_np_dtype(self.compute_dtype)))
            )
        if self._resident_state.master.size:
            self.resident = jax.device_put(
                _unflatten_like(self.resident, self._resident_state.master, self.compute_dtype),
                self._replicated,
            )
        self.store.flush()


def _accumulate_flat(acc: np.ndarray, tree) -> None:
    """acc += flattened-concatenated fp32 leaves of ``tree`` (one device_get
    per leaf; the transfer overlaps the already-dispatched next layer)."""
    off = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(jax.device_get(leaf), np.float32).ravel()
        acc[off : off + a.size] += a
        off += a.size


def _unflatten_like(tree, flat: np.ndarray, dtype):
    leaves = jax.tree_util.tree_leaves(tree)
    treedef = jax.tree_util.tree_structure(tree)
    out, off = [], 0
    for l in leaves:
        size = int(np.prod(l.shape))
        out.append(flat[off : off + size].reshape(l.shape).astype(_np_dtype(dtype)))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)
