"""MiCS — Minimal Communication Scale sharding.

Counterpart of the reference's ``deepspeed/runtime/zero/mics.py``
(``MiCS_Init`` :444, ``MiCS_Optimizer``): ZeRO-3 with shard groups smaller
than the world, replicating state across groups so param all-gathers stay
inside a group (intra-node ICI) and only gradients cross groups.

TPU-native mechanism: the mesh carries a ``data_outer`` (replication) axis —
``zero_shard_axes`` excludes it, so the partitioner emits specs that shard
state 1/group-size and replicate across groups, and XLA's partitioner keeps
the param all-gathers on the inner axis while grad reductions span both
(exactly the reference's hierarchical communication pattern, including the
hierarchical all-gather ``mics_hierarchical_params_gather`` — on TPU the
compiler decomposes the two-level gather itself).

Config: ``zero_optimization.mics_shard_size`` (engine maps it onto the mesh,
``engine._apply_mics_mesh``), or set ``mesh.data_outer`` explicitly.
"""

from __future__ import annotations

from deepspeed_tpu.utils.logging import logger


class MiCS_Init:
    """API-parity context (reference ``MiCS_Init``): under GSPMD, params are
    laid out by the partitioner at materialization, so this context only
    validates config — construction-time partitioning has no TPU analog."""

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True, remote_device=None, pin_memory=False, config_dict_or_path=None, config=None, enabled=True, dtype=None, mpu=None):  # noqa: ARG002
        self.enabled = enabled
        if enabled and config_dict_or_path is not None:
            zero = (config_dict_or_path or {}).get("zero_optimization", {})
            if zero.get("mics_shard_size", -1) <= 0:
                logger.warning(
                    "MiCS_Init without zero_optimization.mics_shard_size: "
                    "falling back to full-world ZeRO sharding"
                )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def MiCS_Optimizer(*args, **kwargs):
    """The reference subclasses stage-3; here MiCS is a sharding layout, so
    the standard engine path IS the MiCS optimizer once the mesh has a
    data_outer axis. Raise with guidance instead of silently diverging."""
    raise NotImplementedError(
        "MiCS on TPU is configured declaratively: set "
        "zero_optimization.mics_shard_size (or mesh.data_outer) and use "
        "deepspeed.initialize — the engine's ZeRO partitioner emits the "
        "group-sharded layout"
    )
