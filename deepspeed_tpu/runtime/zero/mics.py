"""MiCS — Minimal Communication Scale sharding.

Counterpart of the reference's ``deepspeed/runtime/zero/mics.py``
(``MiCS_Init`` :444, ``MiCS_Optimizer``): ZeRO-3 with shard groups smaller
than the world, replicating state across groups so param all-gathers stay
inside a group (intra-node ICI) and only gradients cross groups.

TPU-native mechanism: the mesh carries a ``data_outer`` (replication) axis —
``zero_shard_axes`` excludes it, so the partitioner emits specs that shard
state 1/group-size and replicate across groups, and XLA's partitioner keeps
the param all-gathers on the inner axis while grad reductions span both
(exactly the reference's hierarchical communication pattern, including the
hierarchical all-gather ``mics_hierarchical_params_gather`` — on TPU the
compiler decomposes the two-level gather itself).

Config: ``zero_optimization.mics_shard_size`` (engine maps it onto the mesh,
``engine._apply_mics_mesh``), or set ``mesh.data_outer`` explicitly.
"""

from __future__ import annotations

from deepspeed_tpu.utils.logging import logger


class MiCS_Init:
    """API-parity context (reference ``MiCS_Init``): under GSPMD, params are
    laid out by the partitioner at materialization, so this context only
    validates config — construction-time partitioning has no TPU analog."""

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True, remote_device=None, pin_memory=False, config_dict_or_path=None, config=None, enabled=True, dtype=None, mpu=None):  # noqa: ARG002
        self.enabled = enabled
        if enabled and config_dict_or_path is not None:
            zero = (config_dict_or_path or {}).get("zero_optimization", {})
            if zero.get("mics_shard_size", -1) <= 0:
                logger.warning(
                    "MiCS_Init without zero_optimization.mics_shard_size: "
                    "falling back to full-world ZeRO sharding"
                )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def MiCS_Optimizer(
    module,
    init_optimizer=None,
    timers=None,  # noqa: ARG001 - reference signature; engine owns timing
    ds_config=None,
    static_loss_scale: float = 1.0,
    **kwargs,  # noqa: ARG001 - reference stage-3 knobs subsumed by config
):
    """Reference-shaped entry point (``MiCS_Optimizer`` mics.py:335,
    subclassing the stage-3 optimizer). On TPU MiCS is a sharding layout,
    not an optimizer subclass: this adapter builds the standard engine with
    ``mics_shard_size`` applied — ``engine._apply_mics_mesh`` splits the
    mesh into shard groups ('data') × replica groups ('data_outer') and the
    ZeRO partitioner emits the group-sharded state layout. Returns the
    engine (it IS the optimizer: ``backward``/``step``)."""
    import deepspeed_tpu as ds

    config = ds_config if isinstance(ds_config, dict) else getattr(ds_config, "_param_dict", None)
    if config is None:
        raise ValueError("MiCS_Optimizer requires ds_config (dict or DeepSpeedConfig)")
    config = dict(config)
    zero_cfg = dict(config.get("zero_optimization") or {})
    zero_cfg.setdefault("stage", 3)
    if zero_cfg.get("mics_shard_size", -1) <= 0:
        logger.warning(
            "MiCS_Optimizer without zero_optimization.mics_shard_size: "
            "falling back to full-world ZeRO sharding"
        )
    config["zero_optimization"] = zero_cfg
    if static_loss_scale and static_loss_scale != 1.0 and "fp16" not in config:
        config["fp16"] = {"enabled": True, "loss_scale": static_loss_scale}
    engine, _, _, _ = ds.initialize(
        model=module, optimizer=init_optimizer, config=config, dist_init_required=False
    )
    return engine
