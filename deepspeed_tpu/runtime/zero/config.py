"""ZeRO config (reference: ``deepspeed/runtime/zero/config.py``).

Stage semantics on TPU (see ``deepspeed_tpu/runtime/zero/partition.py``):

* stage 0 — replicated params/grads/optimizer state; grad psum over ``data``.
* stage 1 — optimizer state sharded over ``data`` (PartitionSpec on the
  flattened master/opt buffers).
* stage 2 — + gradients reduce-scattered (grad out-shardings on ``data``).
* stage 3 — + parameters sharded over ``data`` (FSDP-style); XLA inserts the
  all-gathers at use points, which *is* the reference's fetch/prefetch
  coordinator, done by the scheduler instead of hooks.
"""

from enum import Enum
from typing import Optional

from pydantic import Field, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel, pp_int
from deepspeed_tpu.runtime.zero.offload_config import (
    DeepSpeedZeroOffloadOptimizerConfig,
    DeepSpeedZeroOffloadParamConfig,
    OffloadDeviceEnum,
)


class ZeroStageEnum(int, Enum):
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: ZeroStageEnum = ZeroStageEnum.disabled
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(pp_int(int(5e8)), ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(pp_int(int(5e8)), ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    sub_group_size: int = Field(pp_int(int(1e9)), ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param"}
    )
    cpu_offload_use_pin_memory: Optional[bool] = None
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer"}
    )

    # comm/compute overlap (runtime/zero/overlap.py): how many layers of the
    # scanned stack the pipelined stage-3 gather runs AHEAD of use (the
    # reference's prefetch coordinator depth). None → 1 when stage 3 and
    # overlap_comm (the default there), off elsewhere; 0 = the explicit
    # use-point gather (same gather structure, zero lookahead — the
    # bit-identical "unpipelined" baseline of the parity suite). In-flight
    # prefetched elements are additionally capped by
    # stage3_prefetch_bucket_size.
    prefetch_layers: Optional[int] = Field(None, ge=0)
    prefetch_bucket_size: int = Field(pp_int(int(5e7)), ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(pp_int(int(1e5)), ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(pp_int(int(1e13)), ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(pp_int(int(1e9)), ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(pp_int(int(1e9)), ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False

    # ZeRO++ knobs
    zero_hpz_partition_size: int = Field(1, ge=0)
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False

    mics_shard_size: int = Field(-1, alias="mics_shard_size")
    mics_hierarchical_params_gather: bool = False

    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True

    @model_validator(mode="after")
    def _overlap_comm_default(self):
        if self.overlap_comm is None:
            object.__setattr__(self, "overlap_comm", self.stage == ZeroStageEnum.weights)
        return self

    @model_validator(mode="before")
    @classmethod
    def _legacy_cpu_offload(cls, values):
        """Deprecated ``cpu_offload*`` keys route to the real offload path —
        never parse-then-silently-no-op (ISSUE 16 config hygiene)."""
        if isinstance(values, dict):
            pin = values.pop("cpu_offload_use_pin_memory", None)
            if values.pop("cpu_offload", None):
                values.setdefault("offload_optimizer", {"device": OffloadDeviceEnum.cpu})
            if values.pop("cpu_offload_param", None):
                values.setdefault("offload_param", {"device": OffloadDeviceEnum.cpu})
            if pin is not None:
                off = values.get("offload_optimizer")
                if isinstance(off, dict):
                    off.setdefault("pin_memory", bool(pin))
                elif off is None:
                    raise ValueError(
                        "cpu_offload_use_pin_memory is set but no offloaded "
                        "optimizer is configured (cpu_offload or "
                        "offload_optimizer.device); the knob would be silently "
                        "ignored — remove it or configure offload_optimizer"
                    )
        return values
