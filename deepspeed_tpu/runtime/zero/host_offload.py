"""ZeRO-Infinity streamed optimizer-state offload (host RAM ⇄ device).

The legacy path (``offload_states.py``) replaces the device optimizer with a
host AVX Adam: numerically close, but a different update rule — and every
step serializes host compute against the device. This module keeps the
EXISTING donated fused-step program family as the update engine and merely
changes where the fp32 master + Adam moments LIVE between steps: pinned host
buffers, streamed device-ward in ``bucket_size``-element buckets through a
depth-2 double-buffered async H2D pipeline (the PR-5 prefetch pattern with
host→device copies instead of all-gathers), updated per-bucket by a donated
jitted program, and streamed back D2H via ``copy_to_host_async`` while the
next bucket computes (PR-8's async-snapshot writer pattern in reverse).

Bit-identity is the contract: the per-bucket update program replays the
engine's ``step_fn``/``update_from_grads`` math op-for-op (scale, clip,
FusedAdam, mixed-precision recast), so offloaded losses, master tree and
fp16 scale trajectory bit-match the on-device path. The streamer itself
performs NO math — it is a buffer manager plus a transfer schedule.

Stream discipline (what the analysis/lint gates check):

* every H2D/D2H goes through the four sanctioned helpers — ``h2d_bucket``,
  ``d2h_bucket``, ``materialize_writes``, ``drain_writes`` — which count
  bytes and time; a host copy anywhere else in the step family is a
  DS-R009 lint error.
* ``stream_schedule()`` DECLARES each transfer and the compute program it
  hides behind; the ``overlap`` analysis pass verifies the declaration and
  reports ``exposed_stream_bytes`` (gated to 0 on the CI config). The
  ``pipeline_read`` / ``pipeline_write`` knobs are the levers: a transfer
  whose pipeline knob is off is declared (and measured) exposed.
* crash contract: host buffers are NEVER trusted across a crash — a kill
  mid-stream (``train.mid_offload_stream``) leaves them torn by design;
  resume rebuilds them from the last committed checkpoint
  (``load_state_dict``/``set_master_leaves``), bit-identically.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist

STREAMED_FORMAT = "streamed"


def split_offload_buckets(leaf_sizes: Sequence[int], bucket_size: int) -> List[List[int]]:
    """Greedy whole-leaf grouping: consecutive leaves pack into one bucket
    while the bucket stays under ``bucket_size`` elements; a single leaf
    larger than the budget gets its own bucket (leaves never split — the
    donated update programs are per-leaf)."""
    if bucket_size < 1:
        raise ValueError(f"bucket_size must be >= 1, got {bucket_size}")
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_elems = 0
    for i, n in enumerate(leaf_sizes):
        if cur and cur_elems + n > bucket_size:
            buckets.append(cur)
            cur, cur_elems = [], 0
        cur.append(i)
        cur_elems += n
    if cur:
        buckets.append(cur)
    return buckets


class HostOffloadStreamer:
    """Host-resident fp32 master + Adam moments, streamed per-bucket.

    Owns three host fp32 buffer sets (master, exp_avg, exp_avg_sq — one
    numpy array per param leaf), the bucket partition, the staged device
    copies of the in-flight buckets, and the pending D2H writebacks. With
    ``pin_memory`` the buffers are allocated once and written back in place
    (stable addresses — the TPU runtime can keep them registered); without
    it writebacks replace the array references.
    """

    def __init__(
        self,
        master_tree: Any,
        offload_config,
        *,
        mixed_precision: bool,
        clock=time.perf_counter,
    ):
        if jax.process_count() > 1:
            raise NotImplementedError(
                "streamed optimizer offload (offload_optimizer.pipeline_*) is "
                "single-process for now: the host buffers hold full leaves"
            )
        cfg = offload_config
        if float(getattr(cfg, "ratio", 1.0)) != 1.0:
            raise ValueError(
                "offload_optimizer.ratio < 1.0 is not supported on the streamed "
                "TPU path (all optimizer state offloads or none does)"
            )
        if int(getattr(cfg, "buffer_count", 0)) < 2:
            raise ValueError(
                "streamed optimizer offload runs a depth-2 double-buffered "
                "pipeline and needs offload_optimizer.buffer_count >= 2; got "
                f"{cfg.buffer_count}"
            )
        self.pin_memory = bool(getattr(cfg, "pin_memory", False))
        self.pipeline_read = bool(getattr(cfg, "pipeline_read", False))
        self.pipeline_write = bool(getattr(cfg, "pipeline_write", False))
        self.mixed_precision = bool(mixed_precision)
        self._clock = clock

        leaves, self.treedef = jax.tree_util.tree_flatten(master_tree)
        self._shardings = [l.sharding for l in leaves]
        self._shapes = [tuple(l.shape) for l in leaves]
        sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        self._buckets = split_offload_buckets(sizes, int(cfg.bucket_size))

        # materialize the initial master on the host (PR-8 snapshot idiom:
        # enqueue every D2H first, then await — the copies pipeline)
        for l in leaves:
            copy_async = getattr(l, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        # np.array(copy=True): device_get can return a VIEW of the device
        # buffer (CPU backend) that a later donated dispatch would clobber —
        # the host buffers must own their memory
        self._master = [np.array(jax.device_get(l), dtype=np.float32, copy=True) for l in leaves]
        self._exp_avg = [np.zeros_like(m) for m in self._master]
        self._exp_avg_sq = [np.zeros_like(m) for m in self._master]
        self.step_count = 0

        # in-flight state: staged H2D buckets and pending D2H writebacks
        self._staged: Dict[int, Tuple[Optional[list], list, list]] = {}
        self._pending: List[Tuple[int, list, list, list]] = []
        self._stats = {
            "h2d_ms": 0.0,
            "d2h_ms": 0.0,
            "exposed_ms": 0.0,
            "h2d_bytes": 0,
            "d2h_bytes": 0,
            "steps": 0,
        }
        n_bytes = 3 * sum(m.nbytes for m in self._master)
        log_dist(
            f"HostOffloadStreamer: {n_bytes / 1024**2:.1f} MB host state in "
            f"{len(self._buckets)} bucket(s) "
            f"(pin_memory={self.pin_memory}, pipeline_read={self.pipeline_read}, "
            f"pipeline_write={self.pipeline_write})",
            ranks=[0],
        )

    # -- bucket geometry ------------------------------------------------
    @property
    def num_buckets(self) -> int:
        return len(self._buckets)

    def bucket_indices(self, bi: int) -> List[int]:
        return self._buckets[bi]

    def _bucket_elems(self, bi: int) -> int:
        return sum(int(np.prod(self._shapes[i])) or 1 for i in self._buckets[bi])

    # -- sanctioned stream helpers --------------------------------------
    # These four methods are the ONLY places this class touches the device.
    # The DS-R009 lint extension flags device_put/device_get/
    # copy_to_host_async anywhere else in the stream method family.

    def h2d_bucket(self, bi: int) -> None:
        """Stage bucket ``bi`` device-ward (async ``device_put`` per leaf,
        sharded per the master shardings). With ``pipeline_read`` the copies
        overlap the in-flight compute; without it the call blocks — a
        deliberately exposed transfer the overlap gate turns red on."""
        if bi in self._staged:
            return
        # a pending writeback targeting this bucket must land first (only
        # reachable when num_buckets == 1: the deferred last-bucket D2H of
        # step N collides with step N+1's first upload)
        if any(p[0] == bi for p in self._pending):
            self.materialize_writes(keep=0)
        t0 = self._clock()
        ms = [jax.device_put(self._exp_avg[i], self._shardings[i]) for i in self._buckets[bi]]
        vs = [jax.device_put(self._exp_avg_sq[i], self._shardings[i]) for i in self._buckets[bi]]
        masters = None
        nbytes = sum(self._exp_avg[i].nbytes * 2 for i in self._buckets[bi])
        if self.mixed_precision:
            # fp32 training keeps master == params on device; only mixed
            # precision streams the fp32 master up
            masters = [jax.device_put(self._master[i], self._shardings[i]) for i in self._buckets[bi]]
            nbytes += sum(self._master[i].nbytes for i in self._buckets[bi])
        if not self.pipeline_read:
            for arr in (masters or []) + ms + vs:
                arr.block_until_ready()
        dt = (self._clock() - t0) * 1e3
        self._stats["h2d_ms"] += dt
        self._stats["h2d_bytes"] += nbytes
        if not self.pipeline_read:
            self._stats["exposed_ms"] += dt
        self._staged[bi] = (masters, ms, vs)

    def d2h_bucket(self, bi: int, new_master: list, new_m: list, new_v: list) -> None:
        """Enqueue bucket ``bi``'s updated master + moments host-ward
        (``copy_to_host_async`` — the PR-8 writer pattern in reverse: the
        copies drain while the NEXT bucket's update computes). Without
        ``pipeline_write`` the writeback materializes immediately (exposed)."""
        t0 = self._clock()
        for arr in list(new_master) + list(new_m) + list(new_v):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self._pending.append((bi, list(new_master), list(new_m), list(new_v)))
        nbytes = sum(self._master[i].nbytes * 3 for i in self._buckets[bi])
        self._stats["d2h_bytes"] += nbytes
        if not self.pipeline_write:
            self.materialize_writes(keep=0)
            dt = (self._clock() - t0) * 1e3
            self._stats["exposed_ms"] += dt
        else:
            dt = (self._clock() - t0) * 1e3
        self._stats["d2h_ms"] += dt

    def materialize_writes(self, keep: int = 0) -> None:
        """Land pending writebacks into the host buffers, oldest first,
        leaving at most ``keep`` in flight (``keep=1`` is the depth-2
        pipeline's steady state: the newest bucket's copies still overlap
        the next compute)."""
        t0 = self._clock()
        while len(self._pending) > keep:
            bi, masters, ms, vs = self._pending.pop(0)
            for k, i in enumerate(self._buckets[bi]):
                self._land(self._master, i, masters[k])
                self._land(self._exp_avg, i, ms[k])
                self._land(self._exp_avg_sq, i, vs[k])
        self._stats["d2h_ms"] += (self._clock() - t0) * 1e3

    def drain_writes(self) -> None:
        """Checkpoint fence: every pending writeback lands before the host
        buffers are snapshotted (a torn snapshot would otherwise mix step
        N and N-1 state)."""
        self.materialize_writes(keep=0)

    def _land(self, bufs: List[np.ndarray], i: int, arr) -> None:
        host = np.asarray(jax.device_get(arr), np.float32).reshape(self._shapes[i])
        if self.pin_memory:
            np.copyto(bufs[i], host)  # stable (pinned) buffer, write in place
        else:
            # own the memory: device_get may hand back a view of the (donated,
            # soon-reused) device buffer
            bufs[i] = np.array(host, dtype=np.float32, copy=True)

    # -- staged-bucket handoff ------------------------------------------
    def take_staged(self, bi: int) -> Tuple[Optional[list], list, list]:
        """Hand bucket ``bi``'s staged device arrays to the update program
        (which donates them). Requires a prior ``h2d_bucket(bi)``."""
        return self._staged.pop(bi)

    def discard_staged(self) -> None:
        """Drop every staged bucket (fp16 overflow: the step is skipped, the
        host state is already authoritative — nothing to write back)."""
        self._staged.clear()

    # -- window (compile.multi_step) composition ------------------------
    def gather_device_state(self):
        """Stream EVERY bucket device-ward for a fused multi-step window:
        the window program wants the whole master/opt tree on device. Goes
        through the sanctioned h2d helper bucket by bucket."""
        for bi in range(self.num_buckets):
            self.h2d_bucket(bi)
        masters: List[Any] = [None] * len(self._master)
        ms: List[Any] = [None] * len(self._master)
        vs: List[Any] = [None] * len(self._master)
        for bi in range(self.num_buckets):
            staged_m, staged_ea, staged_eas = self.take_staged(bi)
            for k, i in enumerate(self._buckets[bi]):
                if staged_m is not None:
                    masters[i] = staged_m[k]
                ms[i] = staged_ea[k]
                vs[i] = staged_eas[k]
        return (masters if self.mixed_precision else None), ms, vs

    def scatter_device_state(self, master_leaves, m_leaves, v_leaves, steps_taken: int) -> None:
        """Stream the window's updated master/moments back host-ward, bucket
        by bucket through the sanctioned d2h helper; the newest bucket's
        copies stay in flight (depth-2 steady state)."""
        for bi in range(self.num_buckets):
            idx = self._buckets[bi]
            self.d2h_bucket(
                bi,
                [master_leaves[i] for i in idx],
                [m_leaves[i] for i in idx],
                [v_leaves[i] for i in idx],
            )
            self.materialize_writes(keep=1)
        self.step_count += int(steps_taken)

    # -- declared transfer schedule (the overlap pass verifies this) ----
    def stream_schedule(self) -> Dict[str, Any]:
        """The stream's declared accounting: every per-step transfer, its
        bytes, and the compute program it hides behind (``None`` = exposed,
        which the gate counts). Mirrors the dispatch order of
        ``_take_streamed_offload_step``: buckets 0/1 upload under the tail
        of fwd/bwd, bucket i+2 uploads while bucket i updates, bucket i
        writes back while bucket i+1 updates, and the last writeback drains
        under the next step's fwd/bwd."""
        n = self.num_buckets
        per_elem_h2d = 12 if self.mixed_precision else 8  # fp32: moments only
        transfers = []
        for bi in range(n):
            if bi < 2:
                hide = "fwd_bwd"
            else:
                hide = f"offload_bucket_update_b{bi - 2}"
            transfers.append(
                {
                    "name": f"h2d_b{bi}",
                    "direction": "h2d",
                    "bytes": self._bucket_elems(bi) * per_elem_h2d,
                    "hide_behind": hide if self.pipeline_read else None,
                }
            )
        for bi in range(n):
            if bi < n - 1:
                hide = f"offload_bucket_update_b{bi + 1}"
            else:
                hide = "fwd_bwd"  # deferred: lands at the next step's fence
            transfers.append(
                {
                    "name": f"d2h_b{bi}",
                    "direction": "d2h",
                    "bytes": self._bucket_elems(bi) * 12,
                    "hide_behind": hide if self.pipeline_write else None,
                }
            )
        return {
            "anchor": "offload_stats",
            "compute_programs": ["fwd_bwd"]
            + [f"offload_bucket_update_b{bi}" for bi in range(n)],
            "transfers": transfers,
        }

    def stream_stats(self) -> Dict[str, Any]:
        out = dict(self._stats)
        out["buckets"] = self.num_buckets
        out["pending_writes"] = len(self._pending)
        return out

    # -- static residency accounting (analysis memory ledger) -----------
    def memory_report(self) -> Dict[str, Any]:
        """Byte-level residency contract for the HBM ledger: the master +
        both moments live in HOST RAM; the device only ever holds the
        staged upload of the bucket about to update plus the in-flight
        writeback of the bucket that just did — a ≤ 2-bucket bound,
        independent of model size. ``device_residency_bound_bytes`` is that
        static bound (the two largest buckets at the full 12-bytes/elem
        writeback footprint); ``staged_bytes``/``pending_bytes`` are the
        actual bytes on device right now."""
        per_elem_staged = 12 if self.mixed_precision else 8
        bucket_bytes = [
            self._bucket_elems(bi) * 12 for bi in range(self.num_buckets)
        ]
        bound = sum(sorted(bucket_bytes, reverse=True)[:2])
        staged = sum(
            self._bucket_elems(bi) * per_elem_staged for bi in self._staged
        )
        pending = sum(self._bucket_elems(p[0]) * 12 for p in self._pending)
        return {
            "master_location": "host",
            "host_bytes": 3 * sum(m.nbytes for m in self._master),
            "buckets": self.num_buckets,
            "bucket_bytes": bucket_bytes,
            "max_bucket_bytes": max(bucket_bytes, default=0),
            "device_residency_bound_bytes": bound,
            "staged_bytes": staged,
            "pending_bytes": pending,
            "device_bytes": staged + pending,
        }

    def note_step(self) -> None:
        self._stats["steps"] += 1

    # -- tree plumbing ---------------------------------------------------
    def unflatten(self, leaves: List[Any]):
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def master_leaves(self) -> List[np.ndarray]:
        """Host copies of the fp32 master (current through the write fence)."""
        self.drain_writes()
        return [m.copy() for m in self._master]

    # -- checkpoint surface (duck-typed to the engine's offload branch) --
    def state_dict(self) -> Dict[str, Any]:
        """Host-resident snapshot: the leaves are ALREADY numpy, so the
        async checkpoint writer persists them without any device round-trip
        (they pass through ``host_snapshot`` untouched). Copies — the live
        buffers keep training while the writer drains."""
        self.drain_writes()
        return {
            "format": STREAMED_FORMAT,
            "step": int(self.step_count),
            "leaves": [
                {
                    "master": self._master[i].copy(),
                    "exp_avg": self._exp_avg[i].copy(),
                    "exp_avg_sq": self._exp_avg_sq[i].copy(),
                }
                for i in range(len(self._master))
            ],
        }

    def _check_format(self, state: Dict[str, Any]) -> None:
        fmt = state.get("format") if isinstance(state, dict) else None
        if fmt != STREAMED_FORMAT:
            raise ValueError(
                "this checkpoint's host-offload state was saved by the legacy "
                f"per-shard offload engine (format={fmt!r}); the streamed "
                "engine cannot adopt it — load with "
                "offload_optimizer.pipeline_read/pipeline_write disabled, or "
                "pass load_optimizer_states=False to restart the moments"
            )

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Rebuild the host buffers from a checkpoint. This is the ONLY
        sanctioned way to repopulate them after a crash — buffers torn by a
        mid-stream kill are never trusted."""
        self._check_format(state)
        self._staged.clear()
        self._pending.clear()
        self.step_count = int(state["step"])
        for i, rec in enumerate(state["leaves"]):
            np.copyto(self._master[i], np.asarray(rec["master"], np.float32))
            np.copyto(self._exp_avg[i], np.asarray(rec["exp_avg"], np.float32))
            np.copyto(self._exp_avg_sq[i], np.asarray(rec["exp_avg_sq"], np.float32))

    def load_master_only(self, state: Dict[str, Any]) -> None:
        """Module-only load: refresh the master, keep fresh moments."""
        self._check_format(state)
        for i, rec in enumerate(state["leaves"]):
            np.copyto(self._master[i], np.asarray(rec["master"], np.float32))

    def set_master_leaves(self, leaves: List[Any]) -> None:
        """Overwrite the host master from host/device arrays (adopting a
        non-offload checkpoint's master or module weights)."""
        self._staged.clear()
        self._pending.clear()
        for i, leaf in enumerate(leaves):
            np.copyto(
                self._master[i],
                np.asarray(jax.device_get(leaf), np.float32).reshape(self._shapes[i]),
            )
