"""Host-offloaded optimizer state (ZeRO-Offload / ZeRO-Infinity).

TPU-native counterpart of the reference's offload paths: stage-1/2 CPU
offload of optimizer state with DeepSpeedCPUAdam
(``stage_1_and_2.py:1101 async_accumulate_grad_in_cpu_via_gpu`` + the host
``_optimizer_step``) and stage-3 NVMe state swapping
(``stage3.py:542 _configure_tensor_swapping``, ``:1712/:1734`` swap-in at
step, ``:885`` swap-out after).

Design: the chip holds only compute-dtype params and the fp32 grad
accumulator; the fp32 master and Adam moments live as host numpy arrays —
one per (param leaf, addressable shard) — updated by the native AVX Adam
(``csrc/adam/cpu_adam.cpp``). Under ``device=nvme`` the moments (and
optionally master) additionally swap to local SSD between steps via the
pipelined swapper, with the next leaf's read prefetched while the current
leaf updates — mirroring ``PipelinedOptimizerSwapper``.

Step flow (replaces the engine's jitted ``_step_fn`` when offload is on):

    device:  grad-sqnorm + overflow flags         (one tiny jitted program)
    host:    per leaf/shard: scale+clip grads, fused AVX Adam on master,
             cast to compute dtype
    device:  rebuilt param arrays from updated host shards
"""

from __future__ import annotations

import math
import os
import tempfile
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam_native import (
    NativeCPUAdam,
    native_adam_available,
)
from deepspeed_tpu.utils.logging import log_dist, logger


class _LeafShard:
    """Host-side state for one addressable shard of one param leaf."""

    __slots__ = ("device", "index", "master", "exp_avg", "exp_avg_sq", "param_id")

    def __init__(self, device, index, master: np.ndarray, param_id: str):
        self.device = device
        self.index = index
        self.master = master  # flat fp32
        self.exp_avg = np.zeros_like(master)
        self.exp_avg_sq = np.zeros_like(master)
        self.param_id = param_id


class HostOffloadAdam:
    """Adam/AdamW whose state lives entirely off-chip."""

    STATE_NAMES = ("exp_avg", "exp_avg_sq")

    def __init__(
        self,
        master_tree: Any,
        compute_dtype,
        offload_config,
        aio_param_dict: Optional[dict] = None,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        adamw_mode: bool = True,
    ):
        if not native_adam_available():
            raise RuntimeError(
                "offload_optimizer requires the native cpu_adam op (g++ build failed?)"
            )
        self.compute_dtype = compute_dtype
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam = NativeCPUAdam(
            betas=betas, eps=eps, weight_decay=weight_decay, adamw_mode=adamw_mode
        )
        self.step_count = 0

        self._leaves, self._treedef = jax.tree_util.tree_flatten(master_tree)
        self._shards: List[List[_LeafShard]] = []
        self._shapes = [l.shape for l in self._leaves]
        self._shardings = [l.sharding for l in self._leaves]
        for li, leaf in enumerate(self._leaves):
            shards = []
            for s in leaf.addressable_shards:
                host = np.asarray(jax.device_get(s.data), dtype=np.float32).ravel().copy()
                shards.append(_LeafShard(s.device, s.index, host, f"leaf{li}_d{s.device.id}"))
            self._shards.append(shards)

        # nvme swapping of moments (master stays in DRAM: it is needed every
        # step, while moments are only touched inside the update — the
        # reference's default split as well)
        self.swapper = None
        if offload_config is not None and str(getattr(offload_config, "device", "none")) in (
            "OffloadDeviceEnum.nvme",
            "nvme",
        ):
            from deepspeed_tpu.runtime.swap_tensor.aio_config import get_aio_config
            from deepspeed_tpu.runtime.swap_tensor.partitioned_optimizer_swapper import (
                PartitionedOptimizerSwapper,
            )

            nvme_path = str(offload_config.nvme_path or tempfile.gettempdir())
            largest = max(
                (sh.master.size for shards in self._shards for sh in shards), default=1
            )
            self.swapper = PartitionedOptimizerSwapper(
                swap_config=offload_config,
                aio_config=get_aio_config(aio_param_dict or {}),
                base_folder=os.path.join(nvme_path, "ds_tpu_swap"),
                largest_numel=largest,
                device_id=jax.process_index(),
            )
            for shards in self._shards:
                for sh in shards:
                    self.swapper.register_param(sh.param_id, sh.master.size, self.STATE_NAMES)
                    self.swapper.swap_out_param(
                        sh.param_id,
                        {"exp_avg": sh.exp_avg, "exp_avg_sq": sh.exp_avg_sq},
                    )
                    # moments now live on disk; free the DRAM copies
                    sh.exp_avg = None
                    sh.exp_avg_sq = None
        n_bytes = sum(sh.master.nbytes for shards in self._shards for sh in shards)
        log_dist(
            f"HostOffloadAdam: {n_bytes * (3 if self.swapper is None else 1) / 1024**2:.1f} MB "
            f"host state ({'moments on nvme' if self.swapper else 'all in DRAM'})",
            ranks=[0],
        )

    # ------------------------------------------------------------------
    def _flat_shard_ids(self):
        return [
            (li, si)
            for li, shards in enumerate(self._shards)
            for si in range(len(shards))
        ]

    def set_param_dtypes(self, dtypes: List[Any]) -> None:
        """Per-leaf target dtypes for the rebuilt params (keep_fp32_params
        leaves stay fp32 under mixed precision — the same invariant the
        fused device step keeps via m.astype(p.dtype))."""
        self._param_dtypes = list(dtypes)

    def set_master_leaves(self, leaves: List[Any]) -> None:
        """Overwrite the host master from device/host arrays (checkpoint load,
        GatheredParameters write-back). The per-device fast path applies only
        when the incoming array's shard layout matches the master's; anything
        else (host numpy, replicated or differently-sharded arrays) goes
        through full-array slicing."""
        for li, leaf in enumerate(leaves):
            arr = leaf
            for sh in self._shards[li]:
                placed = False
                if hasattr(arr, "addressable_shards"):
                    for s in arr.addressable_shards:
                        if s.device == sh.device and int(np.prod(s.data.shape)) == sh.master.size:
                            sh.master[:] = (
                                np.asarray(jax.device_get(s.data), np.float32).ravel()
                            )
                            placed = True
                            break
                if not placed:
                    sh.master[:] = (
                        np.asarray(jax.device_get(arr), np.float32)[sh.index].ravel()
                    )

    def step(self, grad_leaves: List[Any], lr: float, inv_scale: float, clip_coef: float):
        """Apply one update. ``grad_leaves`` are the device grad-accum arrays
        in the same order as the master leaves; returns new param leaves in
        each leaf's target dtype (list, caller unflattens)."""
        self.step_count += 1
        ids = self._flat_shard_ids()
        new_leaf_shards: List[List[jax.Array]] = [[] for _ in self._shards]

        # prefetch the first leaf's moments while grads land on host
        if self.swapper is not None and ids:
            li0, si0 = ids[0]
            self.swapper.prefetch_param(self._shards[li0][si0].param_id)

        for k, (li, si) in enumerate(ids):
            sh = self._shards[li][si]
            grad_shard = None
            for s in grad_leaves[li].addressable_shards:
                if s.device == sh.device:
                    grad_shard = s
                    break
            assert grad_shard is not None, "grad/master sharding mismatch"
            g_np = np.asarray(jax.device_get(grad_shard.data), dtype=np.float32)
            # grad shards can be COARSER than master shards (stage<2 keeps
            # grads replicated while master is ZeRO-sharded): slice the
            # master's global index relative to the grad shard's
            g = _relative_slice(g_np, grad_shard.index, sh.index).ravel()
            coef = inv_scale * clip_coef
            if coef != 1.0:
                g = g * coef

            if self.swapper is not None:
                m = np.empty_like(sh.master)
                v = np.empty_like(sh.master)
                self.swapper.fetch_param(sh.param_id, {"exp_avg": m, "exp_avg_sq": v})
                if k + 1 < len(ids):
                    lj, sj = ids[k + 1]
                    self.swapper.prefetch_param(self._shards[lj][sj].param_id)
            else:
                m, v = sh.exp_avg, sh.exp_avg_sq

            self.adam.step(sh.master, g, m, v, step=self.step_count, lr=lr)

            if self.swapper is not None:
                self.swapper.writeback_param(sh.param_id, {"exp_avg": m, "exp_avg_sq": v})

            target = (
                self._param_dtypes[li]
                if getattr(self, "_param_dtypes", None) is not None
                else self.compute_dtype
            )
            out = sh.master.astype(_np_dtype(target)).reshape(
                _index_shape(sh.index, self._shapes[li])
            )
            new_leaf_shards[li].append(jax.device_put(out, sh.device))

        if self.swapper is not None:
            self.swapper.drain_writes()

        new_leaves = []
        for li, per_dev in enumerate(new_leaf_shards):
            new_leaves.append(
                jax.make_array_from_single_device_arrays(
                    self._shapes[li], self._param_sharding(li), per_dev
                )
            )
        return new_leaves

    def _param_sharding(self, li: int):
        return self._shardings[li]

    def unflatten(self, leaves: List[Any]):
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # --- checkpoint surface ----------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        state: Dict[str, Any] = {"step": self.step_count, "leaves": []}
        for li, shards in enumerate(self._shards):
            per = []
            for sh in shards:
                if self.swapper is not None:
                    m = np.empty_like(sh.master)
                    v = np.empty_like(sh.master)
                    self.swapper.fetch_param(sh.param_id, {"exp_avg": m, "exp_avg_sq": v})
                    self.swapper.writeback_param(
                        sh.param_id, {"exp_avg": m, "exp_avg_sq": v}
                    )
                else:
                    m, v = sh.exp_avg, sh.exp_avg_sq
                per.append(
                    {
                        "index": _index_repr(sh.index),
                        "master": sh.master.copy(),
                        "exp_avg": m.copy(),
                        "exp_avg_sq": v.copy(),
                    }
                )
            state["leaves"].append(per)
        if self.swapper is not None:
            self.swapper.drain_writes()
        return state

    def drain_writes(self) -> None:
        """Write fence — streamed-engine (host_offload.py) API parity so the
        checkpoint path can fence either flavor; the legacy path has no
        deferred writebacks (``state_dict()`` drains the swapper inline)."""

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        if isinstance(state, dict) and state.get("format") == "streamed":
            raise ValueError(
                "this checkpoint's offloaded optimizer state was saved by the "
                "STREAMED ZeRO-Infinity engine (runtime/zero/host_offload.py); "
                "load it with offload_optimizer.pipeline_read/pipeline_write "
                "enabled, or pass load_optimizer_states=False to adopt the "
                "module weights only"
            )
        self.step_count = int(state["step"])
        for li, per in enumerate(state["leaves"]):
            for sh, rec in zip(self._shards[li], per):
                sh.master[:] = np.asarray(rec["master"], np.float32).ravel()
                m = np.asarray(rec["exp_avg"], np.float32).ravel()
                v = np.asarray(rec["exp_avg_sq"], np.float32).ravel()
                if self.swapper is not None:
                    self.swapper.swap_out_param(
                        sh.param_id, {"exp_avg": m, "exp_avg_sq": v}
                    )
                else:
                    sh.exp_avg[:] = m
                    sh.exp_avg_sq[:] = v

    def load_master_only(self, state: Dict[str, Any]) -> None:
        """Restore just the fp32 master (module-only checkpoint load)."""
        if isinstance(state, dict) and state.get("format") == "streamed":
            raise ValueError(
                "streamed-format (ZeRO-Infinity) offload checkpoint cannot "
                "restore into the legacy host-Adam engine; enable "
                "offload_optimizer.pipeline_read/pipeline_write to load it"
            )
        for li, per in enumerate(state["leaves"]):
            for sh, rec in zip(self._shards[li], per):
                sh.master[:] = np.asarray(rec["master"], np.float32).ravel()

    def master_leaves(self) -> List[np.ndarray]:
        """Full-precision host view of each leaf's local shards (for
        save_checkpoint / fragment access)."""
        out = []
        for li, shards in enumerate(self._shards):
            per = [
                # per-SHARD placement by design: each host fragment goes to
                # exactly its owning device and the NamedSharding reassembles
                # them below — never the whole buffer on one chip
                jax.device_put(  # lint: allow(DS-R011)
                    sh.master.reshape(_index_shape(sh.index, self._shapes[li])), sh.device
                )
                for sh in shards
            ]
            out.append(
                jax.make_array_from_single_device_arrays(
                    self._shapes[li], self._shardings[li], per
                )
            )
        return out


def _np_dtype(jax_dtype):
    return np.dtype(jnp.dtype(jax_dtype).name)


def _relative_slice(data: np.ndarray, outer_index, inner_index) -> np.ndarray:
    """View of ``data`` (the shard at global ``outer_index``) covering the
    global ``inner_index``; requires inner ⊆ outer per dimension."""
    rel = []
    for sl_out, sl_in, dim in zip(outer_index, inner_index, data.shape):
        o_start = sl_out.start or 0
        i_start = sl_in.start or 0
        i_stop = sl_in.stop if sl_in.stop is not None else o_start + dim
        rel.append(slice(i_start - o_start, i_stop - o_start))
    return data[tuple(rel)]


def _index_shape(index, full_shape):
    """Shape of the shard selected by an addressable-shard index tuple."""
    out = []
    for sl, dim in zip(index, full_shape):
        start, stop, _ = sl.indices(dim)
        out.append(stop - start)
    return tuple(out)


def _index_repr(index):
    return [(sl.start, sl.stop) for sl in index]
