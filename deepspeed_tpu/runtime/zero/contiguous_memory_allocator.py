"""Contiguous memory allocator (reference:
``deepspeed/runtime/zero/contiguous_memory_allocator.py``).

Manages one flat host buffer with allocate/release/defragment — the
reference uses it to keep ZeRO-3 partitioned params fragmentation-free.
On TPU, HBM is managed by the XLA allocator, so this class serves the host
side (offload staging, swap buffers) and API parity: tensors are numpy
views into the flat buffer, moved (with their registered ids) during
defragmentation.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class ContiguousMemoryAllocator:
    def __init__(self, size: int, dtype=np.float32, device: str = "cpu"):  # noqa: ARG002
        self.buffer = np.zeros(size, dtype=dtype)
        self.total_size = size
        # contiguous free regions: start -> size
        self.contiguous_sizes: Dict[int, int] = {0: size}
        # allocated regions: start -> size
        self.tensor_sizes: Dict[int, int] = {}
        self.tensor_addresses: Dict[int, int] = {}  # id -> start
        self.tensor_map: Dict[int, np.ndarray] = {}  # id -> view
        self.count = 0
        self.available_memory = size

    # --- allocation ------------------------------------------------------
    def allocate_tensor(self, size: int) -> np.ndarray:
        """A flat view of ``size`` elements; defragments when no contiguous
        region fits but total free memory does (reference :51)."""
        if size > self.available_memory:
            raise RuntimeError(
                f"out of memory: need {size}, available {self.available_memory}"
            )
        start = self._best_fit(size)
        if start is None:
            self.defragment()
            start = self._best_fit(size)
            assert start is not None, "defragmentation failed to produce a fit"
        self._carve(start, size)
        self.count += 1
        tid = self.count
        view = self.buffer[start : start + size]
        self.tensor_addresses[tid] = start
        self.tensor_sizes[start] = size
        self.tensor_map[tid] = view
        self.available_memory -= size
        return view

    def tensor_id(self, view: np.ndarray) -> int:
        for tid, v in self.tensor_map.items():
            if v.base is self.buffer and v is view or (
                v.shape == view.shape and np.shares_memory(v, view)
            ):
                return tid
        raise KeyError("tensor not from this allocator")

    def release_tensor(self, view: np.ndarray) -> None:
        tid = self.tensor_id(view)
        self.release_tensor_with_id(tid)

    def release_tensor_with_id(self, tid: int) -> None:
        start = self.tensor_addresses.pop(tid)
        size = self.tensor_sizes.pop(start)
        self.tensor_map.pop(tid)
        self.available_memory += size
        self._free(start, size)

    # --- defragmentation -------------------------------------------------
    def defragment(self) -> None:
        """Compact all live tensors to the front (reference defragmentation);
        registered views are re-pointed at their new locations."""
        live = sorted(
            ((start, tid) for tid, start in self.tensor_addresses.items())
        )
        cursor = 0
        new_addresses: Dict[int, int] = {}
        new_sizes: Dict[int, int] = {}
        for start, tid in live:
            size = self.tensor_sizes[start]
            if start != cursor:
                self.buffer[cursor : cursor + size] = self.buffer[start : start + size]
            new_addresses[tid] = cursor
            new_sizes[cursor] = size
            self.tensor_map[tid] = self.buffer[cursor : cursor + size]
            cursor += size
        self.tensor_addresses = new_addresses
        self.tensor_sizes = new_sizes
        self.contiguous_sizes = (
            {cursor: self.total_size - cursor} if cursor < self.total_size else {}
        )

    def get_tensor(self, tid: int) -> np.ndarray:
        """Current view for an id (views move on defragment)."""
        return self.tensor_map[tid]

    # --- internals -------------------------------------------------------
    def _best_fit(self, size: int):
        best = None
        for start, free in self.contiguous_sizes.items():
            if free >= size and (best is None or free < self.contiguous_sizes[best]):
                best = start
        return best

    def _carve(self, start: int, size: int) -> None:
        free = self.contiguous_sizes.pop(start)
        if free > size:
            self.contiguous_sizes[start + size] = free - size

    def _free(self, start: int, size: int) -> None:
        self.contiguous_sizes[start] = size
        # merge adjacent free regions
        merged = True
        while merged:
            merged = False
            for s in sorted(self.contiguous_sizes):
                end = s + self.contiguous_sizes[s]
                if end in self.contiguous_sizes:
                    self.contiguous_sizes[s] += self.contiguous_sizes.pop(end)
                    merged = True
                    break
