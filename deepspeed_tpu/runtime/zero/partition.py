"""ZeRO partitioning as sharding-spec emission.

TPU-native heart of the ZeRO stack. The reference mutates flat buffers and
drives gathers from hooks (``deepspeed/runtime/zero/stage_1_and_2.py:95``,
``stage3.py:72``, ``partition_parameters.py:301``); on TPU the same memory
states are *declared* as ``PartitionSpec``s over the ``(data, expert[, sequence])``
mesh axes and the XLA SPMD partitioner inserts the reduce-scatters /
all-gathers, scheduled and overlapped by the compiler (which subsumes the
reference's prefetch coordinator, ``partitioned_param_coordinator.py``):

* stage 0 — everything replicated; grads psum over DP.
* stage 1 — fp32 master + optimizer moments sharded (1/dp each); grads
  reduced full; update runs on the owner shard; updated bf16 params
  all-gathered back (= stage_1_and_2.py ``step`` :1705 semantics).
* stage 2 — + gradient accumulation buffers sharded (reduce-scatter instead
  of all-reduce; ``average_tensor`` :961 semantics).
* stage 3 — + bf16 compute params stored sharded; all-gathered at use.

Per-param sharding picks the largest dimension divisible by the ZeRO world
size — including a TP-sharded dim that can absorb the ZeRO axes on top
(FSDP+TP stacking; on ties an unsharded dim wins). Stacking matters for
gather tables: a vocab-parallel embedding keeps its hidden dim full so
lookups don't produce H-sharded activations. Small params below
``param_persistence_threshold`` stay replicated (the reference's persistent
params, parameter_offload.py:360).
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import Topology
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig, ZeroStageEnum


def _spec_entries(spec: Optional[PartitionSpec], ndim: int) -> list:
    entries = list(spec) if spec is not None else []
    entries += [None] * (ndim - len(entries))
    return entries


def _axes_in_use(entries) -> set:
    used = set()
    for e in entries:
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            used.update(e)
        else:
            used.add(e)
    return used


def shard_over_zero_axes(
    shape: Tuple[int, ...],
    topo: Topology,
    base_spec: Optional[PartitionSpec] = None,
    threshold: int = 0,
    axes: Optional[Tuple[str, ...]] = None,
) -> PartitionSpec:
    """Add ZeRO (data) sharding to ``base_spec`` (which may carry TP axes).

    Chooses the largest dim divisible by the ZeRO world size — an unsharded
    dim, or a TP-sharded dim whose size also absorbs the ZeRO axes stacked
    on top (ties prefer the unsharded dim). Falls back to replicated if none
    qualifies or the param is below ``threshold`` elements. ``axes``
    overrides the topology's default ZeRO axes (hpZ shards masters over more
    axes than params).
    """
    zero_axes = axes if axes is not None else topo.zero_shard_axes
    zero_size = int(np.prod([topo.axis_size(a) for a in zero_axes]))
    entries = _spec_entries(base_spec, len(shape))
    if zero_size == 1:
        return PartitionSpec(*entries)
    n_elements = int(np.prod(shape)) if shape else 0
    if n_elements < max(threshold, 1) or not shape:
        return PartitionSpec(*entries)
    if set(zero_axes) & _axes_in_use(entries):
        return PartitionSpec(*entries)

    # candidates: unsharded dims, OR TP-sharded dims that can absorb the
    # ZeRO axes on top (vocab-parallel embeddings: stacking ZeRO onto the
    # 'model' vocab dim keeps the hidden dim full, so lookups don't produce
    # H-sharded activations that XLA must replicate-reshard). Prefer the
    # largest dim; on ties, the unsharded one.
    candidates = []
    for i, (dim_size, e) in enumerate(zip(shape, entries)):
        if e is None:
            if dim_size % zero_size == 0:
                candidates.append((dim_size, 1, i, None))
        else:
            existing = tuple(e) if isinstance(e, (tuple, list)) else (e,)
            tp_size = int(np.prod([topo.axis_size(a) for a in existing]))
            if dim_size % (tp_size * zero_size) == 0:
                candidates.append((dim_size, 0, i, existing))
    if not candidates:
        return PartitionSpec(*entries)
    _, _, best, existing = max(candidates)
    if existing is None:
        entries[best] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    else:
        entries[best] = existing + tuple(zero_axes)
    return PartitionSpec(*entries)


class ZeroPartitioner:
    """Emits the sharding trees for a param pytree given stage + topology."""

    def __init__(
        self,
        zero_config: DeepSpeedZeroConfig,
        topo: Topology,
        tp_spec_tree: Any = None,
    ):
        self.config = zero_config
        self.stage = int(zero_config.stage)
        self.topo = topo
        self.tp_spec_tree = tp_spec_tree
        # hpZ: the bf16 param store shards only within the hpz group (the
        # inner 'data' axis after the mesh split, reference
        # partition_parameters.py:1490 secondary tensor) while master/grads
        # stay on the full DP world — so master/grad specs add 'data_outer'.
        self.hpz = int(getattr(zero_config, "zero_hpz_partition_size", 1) or 1) > 1

    def _full_dp_axes(self) -> Optional[Tuple[str, ...]]:
        if not self.hpz:
            return None
        return self.topo.data_parallel_axes

    def _tp_spec(self, path_spec) -> Optional[PartitionSpec]:
        return path_spec

    def _map(self, params: Any, fn) -> Any:
        """tree_map over (param, tp_spec) pairs; tp specs default to None."""
        if self.tp_spec_tree is None:
            return jax.tree_util.tree_map(lambda p: fn(p, None), params)
        return jax.tree_util.tree_map(fn, params, self.tp_spec_tree)

    # --- spec trees -----------------------------------------------------
    def param_specs(self, params: Any) -> Any:
        """Sharding of the live (compute-dtype) parameter store."""

        def fn(p, tp):
            if self.stage >= int(ZeroStageEnum.weights):
                return shard_over_zero_axes(
                    np.shape(p), self.topo, tp, threshold=int(self.config.param_persistence_threshold)
                )
            return PartitionSpec(*_spec_entries(tp, np.ndim(p)))

        return self._map(params, fn)

    def master_specs(self, params: Any) -> Any:
        """Sharding of fp32 master weights + optimizer moments (stage >= 1)."""

        def fn(p, tp):
            if self.stage >= int(ZeroStageEnum.optimizer_states):
                return shard_over_zero_axes(
                    np.shape(p), self.topo, tp, threshold=0, axes=self._full_dp_axes()
                )
            return PartitionSpec(*_spec_entries(tp, np.ndim(p)))

        return self._map(params, fn)

    def grad_accum_specs(self, params: Any) -> Any:
        """Sharding of gradient-accumulation buffers (stage >= 2 shards them)."""

        def fn(p, tp):
            if self.stage >= int(ZeroStageEnum.gradients):
                return shard_over_zero_axes(
                    np.shape(p), self.topo, tp, threshold=0, axes=self._full_dp_axes()
                )
            return PartitionSpec(*_spec_entries(tp, np.ndim(p)))

        return self._map(params, fn)

    # --- materialization -------------------------------------------------
    def shardings(self, spec_tree: Any) -> Any:
        mesh = self.topo.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec_tree,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )

    def donation_out_shardings(self, *spec_trees: Any) -> Tuple[Any, ...]:
        """NamedSharding trees for a donated-state output tuple.

        Buffer donation (``jit donate_argnums``) only aliases a donated
        input into an output whose sharding — hence device byte layout — is
        identical. A step program that donates (params, master, opt_state,
        grad_acc, scale_state) must therefore pin ``out_shardings`` to
        exactly the input sharding trees: an omitted or re-derived
        out-sharding lets the partitioner pick a different layout and
        silently turns the in-place update into a copy, double-buffering
        the whole training state in HBM. This helper is the single place
        that materializes those trees, so the donation contract is explicit
        at the call site."""
        return tuple(self.shardings(t) for t in spec_trees)


def estimate_zero_memory(
    n_params: int,
    stage: int,
    dp_size: int,
    bytes_per_param: int = 2,
    optimizer_factor: int = 12,
) -> dict:
    """Counterpart of ``estimate_zero2/3_model_states_mem_needs`` (runtime/utils.py).

    Returns bytes per chip for params/grads/optimizer state under each stage.
    ``optimizer_factor=12``: fp32 master (4) + Adam m (4) + v (4).
    """
    params = n_params * bytes_per_param
    grads = n_params * bytes_per_param
    opt = n_params * optimizer_factor
    if stage >= 1:
        opt = math.ceil(opt / dp_size)
    if stage >= 2:
        grads = math.ceil(grads / dp_size)
    if stage >= 3:
        params = math.ceil(params / dp_size)
    return {
        "params_bytes": params,
        "grads_bytes": grads,
        "optimizer_bytes": opt,
        "total_bytes": params + grads + opt,
    }
