"""Comm/compute overlap for ZeRO training: the software-pipeline plan.

The reference hides ZeRO communication behind compute with a prefetch
coordinator (``partitioned_param_coordinator.py`` driven by
``stage3_prefetch_bucket_size`` / ``overlap_comm``) and reduces gradients in
buckets while backward is still running (``stage_1_and_2.py:961``
``average_tensor``). Our GSPMD port declared those knobs but left the
schedule to XLA — which gathers each scanned layer's shards at its use
point and reduces the stacked gradient in one monolithic tail collective.

This module is the mechanism. An :class:`OverlapPlan` is built by the
engine from the ZeRO config + the stacked ``params["layers"]`` sharding
trees and activated (trace-time, via :func:`overlap_scope`) around the
training loss; the model's scanned layer stack then restructures into a
software pipeline:

* **Pipelined parameter gather** (stage 3) — the scan body computes layer
  *i* from a double-buffered carry of already-gathered params while
  issuing the all-gather for layer *i+depth* (``zero.prefetch_layers``,
  capped so in-flight gathered elements honor
  ``stage3_prefetch_bucket_size``). The gather is a
  ``with_sharding_constraint`` from the ZeRO-sharded per-layer spec to the
  spec with the ZeRO axes stripped — exact, so the pipelined step is
  bit-identical to the unpipelined one.
* **Bucketed gradient reduce-scatter** (stage >= 2) — an identity
  ``custom_vjp`` around the per-layer params whose backward pins each
  layer's cotangent to its scattered layout *inside* the backward scan,
  coalescing leaves into ``reduce_bucket_size``-element buckets through
  the ``[world, chunk]`` row layout of
  ``runtime/comm/coalesced_collectives.py`` — one reduce-scatter per
  bucket per layer, issued as backward produces it, instead of one tail
  barrier over the whole stacked gradient. The packing is pure data
  movement (transpose + pad + concat), so values are unchanged.

Both transforms are value-preserving by construction; the parity suite
(tests/unit/runtime/zero/test_overlap.py) enforces bit-identity against
the unpipelined step, and the ``overlap`` analysis pass verifies the
compiled schedule actually has compute to hide each loop collective
behind.
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.comm.coalesced_collectives import (
    pack_row_coalesced,
    unpack_row_coalesced,
)

_is_spec = lambda x: isinstance(x, P)  # noqa: E731


def _entry_axes(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(a for a in entry if a is not None)
    return (entry,)


def _strip_axes(entry, drop: set):
    kept = tuple(a for a in _entry_axes(entry) if a not in drop)
    if not kept:
        return None
    if len(kept) == 1:
        return kept[0]
    return kept


@dataclass
class _LeafInfo:
    """Static per-leaf metadata for one unstacked ``params['layers']`` leaf."""

    shape: Tuple[int, ...]  # per-layer (unstacked) shape
    gather_spec: P  # per-layer spec with ZeRO axes stripped (the gather target)
    grad_spec: P  # per-layer grad spec (the scattered reduce target)
    scatter_dim: int  # dim of grad_spec carrying the ZeRO axes; -1 if none
    coalescable: bool  # ZeRO axes are the ONLY sharding → row-layout packable


@dataclass
class OverlapPlan:
    """Trace-time comm-overlap schedule for one engine's scanned layer stack."""

    mesh: Any
    zero_axes: Tuple[str, ...]
    zero_world: int
    depth: int  # layers gathered AHEAD of use; 0 = explicit use-point gather
    prefetch_enabled: bool
    reduce_enabled: bool
    reduce_bucket_elems: int
    leaves: List[_LeafInfo] = field(default_factory=list)
    treedef: Any = None
    # --- a2a stage (expert-parallel MoE dispatch/combine) --------------
    # The MoE layer family reads these through active_plan() while tracing:
    # a2a_axis names the mesh axis the dispatch/combine all-to-alls run
    # over, and a2a_quantized selects the int8 wire format of
    # moe/a2a.py:quantized_all_to_all (None defers to the layer's own
    # knob). The a2as themselves are emitted by the layer — dispatch
    # before the shared-expert/dense branch so XLA schedules it behind
    # that independent compute, combine before the next layer's gating —
    # and the overlap analysis pass verifies the schedule has real
    # compute to hide each one behind.
    a2a_axis: Optional[str] = None
    a2a_world: int = 1
    a2a_quantized: Optional[bool] = None

    @property
    def a2a_enabled(self) -> bool:
        return self.a2a_axis is not None and self.a2a_world > 1

    # --- pipelined parameter gather ------------------------------------
    def pin_gathered(self, per_layer: Any) -> Any:
        """Re-pin an already-gathered per-layer tree to the gathered
        sharding. Applied where the carried double buffer is CONSUMED: the
        partitioner unifies a while carry's sharding across init, body root
        and body uses, and the autodiff-saved carry stack pulls it toward
        the sharded layout — without this use-point anchor the carry gets
        resharded and the use re-gathers, silently undoing the pipeline."""
        flat, treedef = jax.tree_util.tree_flatten(per_layer)
        out = [
            jax.lax.with_sharding_constraint(
                t, NamedSharding(self.mesh, info.gather_spec)
            )
            for t, info in zip(flat, self.leaves)
        ]
        return jax.tree_util.tree_unflatten(treedef, out)

    def gather_layer(self, stacked: Any, i) -> Any:
        """Slice layer ``i`` from the stacked [L, ...] tree and constrain it
        to the gathered (ZeRO-axes-stripped) sharding — the all-gather the
        pipeline issues ahead of use. ``i`` may be a python int (prologue)
        or a traced scan index."""
        flat, treedef = jax.tree_util.tree_flatten(stacked)
        out = []
        for leaf, info in zip(flat, self.leaves):
            t = jax.lax.dynamic_index_in_dim(leaf, i, axis=0, keepdims=False)
            out.append(
                jax.lax.with_sharding_constraint(
                    t, NamedSharding(self.mesh, info.gather_spec)
                )
            )
        return jax.tree_util.tree_unflatten(treedef, out)

    def use_buffered(self, stacked: Any, buf: Any, i) -> Any:
        """Consume a prefetched per-layer buffer with USE-POINT autodiff.

        Forward: the double-buffered carry value (the gather issued
        ``depth`` layers ago — the schedule the pipeline exists for).
        Backward: ``jax.linear_transpose`` of :meth:`gather_layer` at this
        layer's own index — the exact transpose the depth-0 use-point
        gather gets from autodiff, scattering the cotangent straight into
        the stacked tree. Without this, the buffer's cotangent travels
        back through ``depth`` backward-scan carries and the partitioner
        re-derives the cross-device grad reduction around the carry's
        layout — measured on the 8-device mesh as last-ulp grad drift vs
        depth 0 (all-reduce vs reduce-scatter summation order). Routing
        the cotangent through the same ops as depth 0 makes depth-k
        bit-identical BY CONSTRUCTION; the carried buffers get zero
        cotangent, so their backward path folds away. Sound because the
        pipeline invariant holds bit-wise: buf IS gather_layer(stacked, i)
        — both pure data movement of the same shards."""
        avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), stacked
        )
        bavals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buf
        )

        @jax.custom_vjp
        def _use(stacked, buf, i):
            return buf

        def _fwd(stacked, buf, i):
            return buf, i

        def _bwd(idx, g):
            (d_stacked,) = jax.linear_transpose(
                lambda s: self.gather_layer(s, idx), avals
            )(g)
            d_buf = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, a.dtype), bavals
            )
            d_idx = np.zeros(np.shape(idx), jax.dtypes.float0)
            return (d_stacked, d_buf, d_idx)

        _use.defvjp(_fwd, _bwd)
        return _use(stacked, buf, i)

    # --- bucketed in-scan gradient reduction ---------------------------
    def reduce_grads(self, per_layer: Any) -> Any:
        """Identity on the per-layer param tree whose backward issues this
        layer's gradient reduction right where the layer's backward runs —
        inside the scan, coalesced into ``reduce_bucket_size``-element
        buckets — instead of one monolithic tail barrier.

        The in-loop constraint materializes the cross-batch sum in the
        gathered-over-ZeRO layout (ONE collective per bucket; without it
        XLA emits one per leaf, or defers the whole reduction to the tail).
        The SCATTERED stage-2/3 layout then lands at the engine's grad
        shardings — a free local slice once the sum exists. Pinning the
        scattered layout here instead would fight the transpose
        accumulator's carry sharding: the partitioner keeps that carry
        gathered and answers with a gather-back per layer (measured on the
        8-device mesh), turning the optimization into extra wire traffic."""
        if not self.reduce_enabled:
            return per_layer

        @jax.custom_vjp
        def _reduce_boundary(tree):
            return tree

        def _fwd(tree):
            return tree, None

        def _bwd(_, g):
            return (self._coalesce_cotangent(g),)

        _reduce_boundary.defvjp(_fwd, _bwd)
        return _reduce_boundary(per_layer)

    def _coalesce_cotangent(self, g: Any) -> Any:
        """Coalesce one layer's cotangent tree into element-capped buckets
        via the shared ``[world, chunk]`` row layout and force each
        bucket's reduction with a single gathered-layout constraint. Pure
        data movement around one collective per bucket — values untouched.
        Leaves with TP-mixed sharding stay un-coalesced (their layout is
        not row-packable with the pure-ZeRO leaves)."""
        flat, treedef = jax.tree_util.tree_flatten(g)

        # group coalescable leaves by dtype (a packed buffer is one dtype),
        # then split each group into element-capped buckets, preserving
        # tree order so the bucket layout is deterministic across traces
        groups: dict = {}
        for idx, (leaf, info) in enumerate(zip(flat, self.leaves)):
            if leaf is None:  # symbolic zero cotangent: nothing to reduce
                continue
            if info.coalescable:
                groups.setdefault(str(leaf.dtype), []).append(idx)

        out = list(flat)
        for idxs in groups.values():
            for bucket in _split_buckets(
                idxs, [self.leaves[i] for i in idxs], self.reduce_bucket_elems
            ):
                infos = [self.leaves[i] for i in bucket]
                if len(bucket) == 1:
                    i, info = bucket[0], infos[0]
                    out[i] = jax.lax.with_sharding_constraint(
                        flat[i], NamedSharding(self.mesh, info.gather_spec)
                    )
                    continue
                moved = [
                    jnp.moveaxis(flat[i], info.scatter_dim, 0)
                    for i, info in zip(bucket, infos)
                ]
                buf = pack_row_coalesced(moved, self.zero_world)
                # ONE reduction for the whole bucket (coalescable leaves are
                # pure-ZeRO sharded, so gathered-over-ZeRO == replicated)
                buf = jax.lax.with_sharding_constraint(
                    buf, NamedSharding(self.mesh, P(None, None))
                )
                parts = unpack_row_coalesced(
                    buf, [m.shape for m in moved], self.zero_world
                )
                for i, info, part in zip(bucket, infos, parts):
                    out[i] = jnp.moveaxis(part, 0, info.scatter_dim)
        return jax.tree_util.tree_unflatten(treedef, out)


def _entry_axes_nonempty(spec: P) -> bool:
    return any(_entry_axes(e) for e in spec)


def _split_buckets(
    idxs: List[int], infos: List[_LeafInfo], cap_elems: int
) -> List[List[int]]:
    """Greedy size-targeted grouping (reference ``reduce_bucket_size``
    semantics: element count per collective). Every bucket holds >= 1 leaf;
    an oversized single leaf rides alone."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_elems = 0
    cap = max(int(cap_elems), 1)
    for i, info in zip(idxs, infos):
        n = int(np.prod(info.shape)) if info.shape else 1
        if cur and cur_elems + n > cap:
            buckets.append(cur)
            cur, cur_elems = [], 0
        cur.append(i)
        cur_elems += n
    if cur:
        buckets.append(cur)
    return buckets


def build_overlap_plan(
    zero_config,
    topo,
    stacked_tree: Any,
    stacked_param_specs: Any,
    stacked_grad_specs: Any,
    num_layers: int,
    moe_quantized_a2a: Optional[bool] = None,
) -> Optional[OverlapPlan]:
    """Build the plan from the ZeRO config + the STACKED ``params['layers']``
    trees (arrays-or-shaped leaves + param/grad PartitionSpecs, leading dim
    = L). Returns None when no stage is enabled: neither ZeRO transform
    (stage < 2, or overlap off with no explicit ``prefetch_layers``) nor
    the expert-parallel a2a stage (mesh has no real ``expert`` axis).

    ``prefetch_layers`` semantics: ``None`` → one layer of lookahead when
    stage-3 overlap is on (the reference's default prefetch), nothing
    otherwise; ``k >= 1`` → a k-deep software pipeline; ``0`` → the
    EXPLICIT use-point gather — the same gather/constraint structure as the
    pipeline but issued at the layer's own iteration, zero lookahead. Depth
    0 is the "unpipelined step" of the parity contract: depth only moves
    where the gather is issued, never what is computed, so depth-k and
    depth-0 programs are bit-identical (the parity suite enforces =, not
    allclose). The raw scan (no plan) lets GSPMD place the gather itself,
    which re-partitions the backward and reassociates the distributed grad
    sum at the last ulp — so raw-vs-explicit is compared at tight rtol
    instead."""
    stage = int(zero_config.stage)
    overlap = bool(zero_config.overlap_comm)
    prefetch_layers = getattr(zero_config, "prefetch_layers", None)
    if prefetch_layers is None and stage >= 3 and overlap:
        prefetch_layers = 1
    prefetch = stage >= 3 and prefetch_layers is not None
    reduce_ = stage >= 2 and overlap and bool(zero_config.reduce_scatter)
    # a2a stage: armed whenever the mesh has a real expert axis — the MoE
    # layer family routes its dispatch/combine exchange through it
    a2a_world = int(topo.axis_size("expert")) if "expert" in topo.mesh.axis_names else 1
    a2a = a2a_world > 1
    if not prefetch and not reduce_ and not a2a:
        return None

    zero_axes = tuple(topo.zero_shard_axes)
    zero_world = int(np.prod([topo.axis_size(a) for a in zero_axes])) if zero_axes else 1
    if zero_world <= 1:
        prefetch = reduce_ = False
        if not a2a:
            return None
    drop = set(zero_axes)
    # size-1 mesh axes don't partition anything: ignore them when deciding
    # what a leaf's "real" sharding is (TP rules emit 'model' entries even
    # on a pure-data mesh), but keep them in the emitted specs
    trivial = {a for a in topo.mesh.axis_names if topo.axis_size(a) == 1}

    arr_flat, treedef = jax.tree_util.tree_flatten(stacked_tree)
    pspecs_flat = treedef.flatten_up_to(stacked_param_specs)
    gspecs_flat = treedef.flatten_up_to(stacked_grad_specs)

    leaves: List[_LeafInfo] = []
    gathered_elems = 0
    for arr, pspec, gspec in zip(arr_flat, pspecs_flat, gspecs_flat):
        shape = tuple(int(d) for d in arr.shape)
        per_shape = shape[1:]
        p_entries = list(pspec) + [None] * (len(shape) - len(list(pspec)))
        g_entries = list(gspec) + [None] * (len(shape) - len(list(gspec)))
        # per-layer view: drop the scanned L dim (entry 0)
        gather_spec = P(*[_strip_axes(e, drop) for e in p_entries[1:]])
        grad_spec = P(*g_entries[1:])
        scatter_dim = -1
        coalescable = False
        for d, e in enumerate(g_entries[1:]):
            axes = _entry_axes(e)
            if set(axes) & drop:
                scatter_dim = d
                # packable iff the ZeRO axes are this leaf's ONLY effective
                # sharding — a TP-stacked dim or a second sharded dim would
                # need its own buffer layout, so it reduces un-coalesced
                others = [
                    a
                    for ee in g_entries[1:]
                    for a in _entry_axes(ee)
                    if a not in drop and a not in trivial
                ]
                effective = tuple(a for a in axes if a not in trivial)
                coalescable = effective == tuple(zero_axes) and not others
                break
        # a leaf whose ZeRO sharding landed on the scanned L dim itself
        # yields an already-replicated per-layer slice — nothing to gather
        if not (set(_entry_axes(p_entries[0])) & drop) and any(
            set(_entry_axes(e)) & drop for e in p_entries[1:]
        ):
            gathered_elems += int(np.prod(per_shape)) if per_shape else 1
        leaves.append(
            _LeafInfo(
                shape=per_shape,
                gather_spec=gather_spec,
                grad_spec=grad_spec,
                scatter_dim=scatter_dim,
                coalescable=coalescable,
            )
        )

    depth = 0
    if prefetch:
        depth = min(int(prefetch_layers), int(num_layers))
        budget = int(zero_config.prefetch_bucket_size)
        if budget > 0 and gathered_elems > 0:
            # cap in-flight prefetched elements (depth layers beyond the one
            # in use) at stage3_prefetch_bucket_size, never below 1 layer
            while depth > 1 and depth * gathered_elems > budget:
                depth -= 1
        if gathered_elems == 0:
            prefetch = False  # nothing is ZeRO-sharded (all persistent)
            depth = 0
    if not prefetch and not reduce_ and not a2a:
        return None

    return OverlapPlan(
        mesh=topo.mesh,
        zero_axes=zero_axes,
        zero_world=zero_world,
        depth=depth,
        prefetch_enabled=prefetch,
        reduce_enabled=reduce_,
        reduce_bucket_elems=int(zero_config.reduce_bucket_size) or 1,
        leaves=leaves,
        treedef=treedef,
        a2a_axis="expert" if a2a else None,
        a2a_world=a2a_world,
        a2a_quantized=moe_quantized_a2a,
    )


# --- trace-time activation --------------------------------------------------
_ACTIVE: List[OverlapPlan] = []


@contextmanager
def overlap_scope(plan: Optional[OverlapPlan]):
    """Activate ``plan`` for the duration of a trace. The engine wraps its
    training-loss closures with this; the model family reads
    :func:`active_plan` while tracing its layer stack."""
    if plan is None:
        yield
        return
    _ACTIVE.append(plan)
    try:
        yield
    finally:
        _ACTIVE.pop()


def active_plan() -> Optional[OverlapPlan]:
    return _ACTIVE[-1] if _ACTIVE else None
