"""Offload configs (reference: ``deepspeed/runtime/zero/offload_config.py``).

On TPU-VMs, "cpu" offload means host-DRAM partitions driven by the C++ host
optimizer; "nvme" means the local SSD via the async-IO library
(``deepspeed_tpu/ops/aio``).
"""

from enum import Enum
from pathlib import Path
from typing import Optional

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel, pp_int


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[Path] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(pp_int(int(1e8)), ge=0)
    max_in_cpu: int = Field(pp_int(int(1e9)), ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[Path] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)
    # streamed (ZeRO-Infinity) path: elements per H2D/D2H bucket — the unit
    # the fp32 master + moments stream through the depth-2 pipeline in
    # (runtime/zero/host_offload.py). Same units as reduce_bucket_size.
    bucket_size: int = Field(pp_int(int(5e7)), ge=1)

    @property
    def pipeline(self) -> bool:
        """True selects the STREAMED offload engine (host buffers + donated
        per-bucket device update) over the legacy host-Adam path."""
        return self.pipeline_read or self.pipeline_write
