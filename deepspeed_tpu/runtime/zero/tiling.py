"""Tiled linear (reference: ``deepspeed/runtime/zero/tiling.py``).

The reference breaks a huge ``nn.Linear`` into an (in_splits × out_splits)
tile grid so ZeRO-3 can partition/offload inactive tiles. Under GSPMD the
partitioner already shards any matmul, so tiling buys nothing for sharding
— what survives is the API (models written against TiledLinear port
unchanged) and the memory shape: per-tile params mean per-tile gathers
under ZeRO-3 instead of one monolithic gather.

Functional: ``init(rng)`` builds the tile tree, ``apply(params, x)`` runs
the tile grid with fp32 partial-sum accumulation over in-tiles.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def split_tensor_along_last_dim(tensor: jnp.ndarray, partitions: int, contiguous_split_chunks: bool = False):  # noqa: ARG001
    """Reference helper: split the last dim into ``partitions`` chunks."""
    return jnp.split(tensor, partitions, axis=-1)


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries of a near-uniform split (reference partition helper)."""
    base = num_items // num_parts
    extra = num_items % num_parts
    bounds = [0]
    for p in range(num_parts):
        bounds.append(bounds[-1] + base + (1 if p < extra else 0))
    return bounds


class TiledLinear:
    """y = x @ W.T + b computed as an (out_splits × in_splits) tile grid.

    Matches the reference's semantics: input split along its last dim into
    ``in_splits`` chunks, each out-tile sums its in-tiles' partial products,
    outputs concatenated unless ``combine_out_splits=False``.
    """

    def __init__(
        self,
        in_features: int,
        out_features: int,
        bias: bool = True,
        in_splits: int = 1,
        out_splits: int = 1,
        input_is_already_split: bool = False,
        combine_out_splits: bool = True,
    ):
        if in_splits < 1 or out_splits < 1:
            raise ValueError("in_splits and out_splits must be >= 1")
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.input_is_already_split = input_is_already_split
        self.combine_out_splits = combine_out_splits
        self.in_bounds = partition_uniform(in_features, in_splits)
        self.out_bounds = partition_uniform(out_features, out_splits)

    def init(self, rng, std: float = 0.02) -> Dict[str, Any]:
        tiles = {}
        keys = jax.random.split(rng, self.in_splits * self.out_splits)
        ki = 0
        for o in range(self.out_splits):
            for i in range(self.in_splits):
                o0, o1 = self.out_bounds[o], self.out_bounds[o + 1]
                i0, i1 = self.in_bounds[i], self.in_bounds[i + 1]
                tiles[f"tile_{o}_{i}"] = (
                    jax.random.normal(keys[ki], (i1 - i0, o1 - o0), jnp.float32) * std
                )
                ki += 1
        params: Dict[str, Any] = {"tiles": tiles}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.out_features,), jnp.float32)
        return params

    def from_full(self, weight: np.ndarray, bias: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Tile a full [in, out] weight (reference ``init_linear`` copy)."""
        if weight.shape != (self.in_features, self.out_features):
            raise ValueError(f"expected [in, out] = {(self.in_features, self.out_features)}, got {weight.shape}")
        tiles = {}
        for o in range(self.out_splits):
            for i in range(self.in_splits):
                o0, o1 = self.out_bounds[o], self.out_bounds[o + 1]
                i0, i1 = self.in_bounds[i], self.in_bounds[i + 1]
                tiles[f"tile_{o}_{i}"] = jnp.asarray(weight[i0:i1, o0:o1])
        params: Dict[str, Any] = {"tiles": tiles}
        if self.use_bias:
            params["bias"] = (
                jnp.asarray(bias) if bias is not None else jnp.zeros((self.out_features,), jnp.float32)
            )
        return params

    def apply(self, params: Dict[str, Any], x):
        if self.input_is_already_split:
            chunks = list(x)
        elif self.in_splits > 1:
            chunks = [
                x[..., self.in_bounds[i] : self.in_bounds[i + 1]]
                for i in range(self.in_splits)
            ]
        else:
            chunks = [x]
        outs = []
        for o in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                part = jnp.dot(
                    chunks[i],
                    params["tiles"][f"tile_{o}_{i}"].astype(chunks[i].dtype),
                    preferred_element_type=jnp.float32,
                )
                acc = part if acc is None else acc + part
            if self.use_bias:
                o0, o1 = self.out_bounds[o], self.out_bounds[o + 1]
                acc = acc + params["bias"][o0:o1].astype(jnp.float32)
            outs.append(acc.astype(x[0].dtype if isinstance(x, (list, tuple)) else x.dtype))
        if self.combine_out_splits:
            return jnp.concatenate(outs, axis=-1)
        return outs


class TiledLinearReturnBias(TiledLinear):
    """Megatron-style variant: returns (output, bias) without adding it."""

    def apply(self, params, x):
        use_bias, self.use_bias = self.use_bias, False
        try:
            out = super().apply(params, x)
        finally:
            self.use_bias = use_bias
        return out, params.get("bias")
