"""ZeRO++ runtime wiring: qwZ / qgZ / hpZ.

Reference: ``deepspeed/runtime/zero/config.py:260-272`` (the three flags),
``partition_parameters.py:654`` (quantized weight all-gather, qwZ),
``partition_parameters.py:1490`` (secondary hpZ partition),
``runtime/comm/coalesced_collectives.py:31`` (quantized grad reduce, qgZ).

TPU-native mapping:

* **qwZ** (``zero_quantized_weights``) — the stage-3 param all-gather carries
  int8. Under GSPMD the gather is implicit, so the quantization is expressed
  as a *resharding boundary*: quantize shard-locally (per-group scales along
  the sharded dim), pin the int8 payload + scales sharded, re-pin them
  replicated — XLA inserts the all-gather **on the int8 arrays** — then
  dequantize. Gradients pass straight through (STE), and XLA's normal
  cotangent reduce-scatter is unchanged.
* **qgZ** (``zero_quantized_gradients``) — XLA's implicit grad reduce
  cannot be quantized (round() does not commute with psum), so the grad
  path switches to an explicit ``shard_map`` over the data axis: per-chip
  partial grads are block-quantized and all-to-all'd (1 int8 hop), then
  summed locally straight into the stage-2/3 scattered layout —
  ≈1 byte/element on the wire vs 2 for a bf16 reduce-scatter and 4 for
  fp32, the reference's 4× claim. Leaves whose accumulation buffer is
  replicated add one int8 all-gather of the sums.
* **hpZ** (``zero_hpz_partition_size``) — the bf16 param store (the gather
  source) is sharded only *within* a group of that size and replicated
  across groups, so gathers ride intra-group ICI; the fp32 master + moments
  stay sharded over the FULL data-parallel world (no optimizer memory is
  given back). Expressed as a data→(data, data_outer) mesh split where
  param specs use the inner axis and master/grad specs use both.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.jax_compat import shard_map

from deepspeed_tpu.ops.quantizer import quantize
from deepspeed_tpu.parallel.mesh import Topology
from deepspeed_tpu.runtime.comm.coalesced_collectives import (
    quant_a2a_reduce_local,
    quant_all_gather_local,
)

_TARGET_GROUP = 2048  # quant-group width target (reference default block)


def _group_count(n: int, target: int = _TARGET_GROUP) -> int:
    """Largest divisor-based group split of ``n`` with groups ≤ target."""
    k = max(1, -(-n // target))  # ceil
    while n % k:
        k += 1
    return k


# ---------------------------------------------------------------------------
# qwZ — int8 param-gather boundary (GSPMD path)
# ---------------------------------------------------------------------------
def _sharded_dim(spec: P, zero_axes) -> int:
    """Index of the dim carrying a ZeRO axis in ``spec``; -1 if none."""
    zset = set(zero_axes)
    for i, e in enumerate(spec):
        entries = e if isinstance(e, (tuple, list)) else (e,)
        if zset & {a for a in entries if a is not None}:
            return i
    return -1


def qwz_gather_tree(params: Any, spec_tree: Any, topo: Topology, num_bits: int = 8) -> Any:
    """Fake-quantized gather of every ZeRO-sharded leaf: the value handed to
    the model is dequantize(quantize(p)) and the wire format of the implicit
    all-gather is int8. Leaves without a ZeRO-sharded dim pass through."""
    mesh = topo.mesh
    zero_axes = topo.zero_shard_axes

    def leaf(p, spec):
        d = _sharded_dim(spec, zero_axes)
        if d < 0 or np.ndim(p) == 0:
            return p
        entry = list(spec)[d]

        @jax.custom_vjp
        def fq_gather(x):
            xt = jnp.moveaxis(x, d, 0)
            lead = xt.shape[0]
            rest = int(np.prod(xt.shape[1:])) if xt.ndim > 1 else 1
            k = _group_count(rest)
            flat = xt.reshape(lead * k, rest // k)
            q, s = quantize(flat, lead * k, num_bits)
            # computed shard-local…
            q = jax.lax.with_sharding_constraint(q, NamedSharding(mesh, P(entry, None)))
            s = jax.lax.with_sharding_constraint(s, NamedSharding(mesh, P(entry)))
            # …gathered as int8…
            q = jax.lax.with_sharding_constraint(q, NamedSharding(mesh, P(None, None)))
            s = jax.lax.with_sharding_constraint(s, NamedSharding(mesh, P(None)))
            # …dequantized replicated
            full = (q.astype(jnp.float32) * s[:, None]).reshape(xt.shape).astype(x.dtype)
            return jnp.moveaxis(full, 0, d)

        def fwd(x):
            return fq_gather(x), None

        def bwd(_, g):
            return (g,)  # STE: XLA reduce-scatters the cotangent as usual

        fq_gather.defvjp(fwd, bwd)
        return fq_gather(p)

    return jax.tree_util.tree_map(
        leaf, params, spec_tree, is_leaf=lambda x: isinstance(x, P)
    )


# ---------------------------------------------------------------------------
# qgZ — explicit quantized gradient all-reduce (shard_map path)
# ---------------------------------------------------------------------------
def validate_qgz_mesh(topo: Topology) -> None:
    bad = {
        ax: topo.axis_size(ax)
        for ax in ("model", "sequence", "expert", "pipe", "data_outer")
        if topo.axis_size(ax) > 1
    }
    if bad:
        raise ValueError(
            "zero_quantized_gradients runs the explicit data-parallel grad "
            f"reduce and supports a pure data-axis mesh; got non-trivial axes {bad}"
        )


def _quantized_reduce_leaf(
    g: jnp.ndarray, grad_spec: P, axis: str, world: int, num_bits: int
) -> jnp.ndarray:
    """Inside shard_map: reduce one partial-grad leaf across the data axis
    with int8 on the wire, averaging the per-chip contributions (each chip
    differentiates its LOCAL-batch mean; the exact path differentiates the
    global mean = sum/world).

    When the leaf's accumulation buffer is sharded (stage ≥ 2), the reduce is
    a pure scatter — one int8 all-to-all, each chip keeps only its own chunk.
    Replicated leaves (stage < 2 / sub-threshold) add an int8 all-gather hop."""
    shape, dtype = g.shape, g.dtype
    d = _sharded_dim(grad_spec, (axis,))
    if d >= 0 and shape[d] % world == 0:
        gt = jnp.moveaxis(g.astype(jnp.float32), d, 0)
        chunk = int(np.prod(gt.shape)) // world
        gpg = _group_count(chunk)
        flat = gt.reshape(-1)
        mine = quant_a2a_reduce_local(flat, axis, world, gpg, num_bits) / world
        local = mine.reshape((gt.shape[0] // world,) + gt.shape[1:])
        return jnp.moveaxis(local, 0, d).astype(dtype)
    # replicated output: scatter-reduce then int8 gather of the sums
    flat = g.astype(jnp.float32).reshape(-1)
    n0 = flat.shape[0]
    gpg = _group_count(max(1, -(-n0 // world)))
    pad = (-n0) % (world * gpg)
    flat = jnp.pad(flat, (0, pad))
    mine = quant_a2a_reduce_local(flat, axis, world, gpg, num_bits) / world
    full = quant_all_gather_local(mine, axis, gpg, num_bits).reshape(-1)
    return full[:n0].reshape(shape).astype(dtype)


def _gather_leaf_local(x_local, spec: P, axis: str, world: int, qwz: bool, num_bits: int):
    """Inside shard_map: materialize the full leaf from its local shard
    (int8 wire when qwZ is also enabled)."""
    d = _sharded_dim(spec, (axis,))
    if d < 0:
        return x_local
    if not qwz:
        return jax.lax.all_gather(x_local, axis, axis=d, tiled=True)
    xt = jnp.moveaxis(x_local, d, 0)
    lead, rest = xt.shape[0], int(np.prod(xt.shape[1:])) if xt.ndim > 1 else 1
    k = _group_count(rest)
    rows = quant_all_gather_local(
        xt.reshape(lead * k, max(1, rest // k)), axis, lead * k, num_bits
    )  # [world, local_size]
    full = rows.reshape((world * lead,) + xt.shape[1:])
    return jnp.moveaxis(full.astype(x_local.dtype), 0, d)


def build_qgz_fwd_bwd(
    loss_of: Callable,
    topo: Topology,
    param_spec_tree: Any,
    grad_spec_tree: Any,
    batch_spec_fn: Callable,
    qwz: bool,
    num_bits: int = 8,
) -> Callable:
    """fwd_bwd(params, grad_acc, scale, rng, batch) for the qgZ path.

    The loss/grad computation runs per chip inside ``shard_map``; sharded
    grad leaves cross the wire in ONE int8 all-to-all (≈1 byte/element vs 2
    for a bf16 reduce-scatter, 4 for fp32 — the reference's 4× claim) and
    land directly in the stage-2/3 scattered layout. Dropout rngs are shared
    across chips (each chip draws the same mask over its own rows) — parity
    tests run with dropout off, like the reference's qgZ tests."""
    mesh: Mesh = topo.mesh
    axis = "data"
    world = topo.axis_size(axis)
    is_spec = lambda v: isinstance(v, P)  # noqa: E731

    def fwd_bwd(params, grad_acc, scale, rng, batch):
        batch_specs = batch_spec_fn(batch)
        # a leaf's reduced grad leaves the shard_map in its accumulation
        # layout: the grad spec when the scatter applies, replicated otherwise
        def out_spec_of(p, sp):
            d = _sharded_dim(sp, (axis,))
            if d >= 0 and np.shape(p)[d] % world == 0:
                return sp
            return P()

        grad_out_specs = jax.tree_util.tree_map(
            out_spec_of, params, grad_spec_tree, is_leaf=is_spec
        )

        def body(p_shards, scale_, rng_, b_local):
            full = jax.tree_util.tree_map(
                lambda x, sp: _gather_leaf_local(x, sp, axis, world, qwz, num_bits),
                p_shards,
                param_spec_tree,
                is_leaf=is_spec,
            )

            def scaled_loss(f):
                return loss_of(f, b_local, rng_) * scale_.astype(jnp.float32)

            loss_local, g = jax.value_and_grad(scaled_loss)(full)
            g = jax.tree_util.tree_map(
                lambda t, sp: _quantized_reduce_leaf(t, sp, axis, world, num_bits),
                g,
                grad_spec_tree,
                is_leaf=is_spec,
            )
            return jax.lax.pmean(loss_local, axis), g

        loss_scaled, grads = shard_map(
            body,
            mesh=mesh,
            in_specs=(param_spec_tree, P(), P(), batch_specs),
            out_specs=(P(), grad_out_specs),
            check_vma=False,
        )(params, scale, rng, batch)
        new_acc = jax.tree_util.tree_map(
            lambda a, g, sp: jax.lax.with_sharding_constraint(
                a + g.astype(jnp.float32), NamedSharding(mesh, sp)
            ),
            grad_acc,
            grads,
            grad_spec_tree,
            is_leaf=is_spec,
        )
        return loss_scaled / scale.astype(jnp.float32), new_acc

    return fwd_bwd


# ---------------------------------------------------------------------------
# hpZ — secondary param partition via the data→(data, data_outer) split
# ---------------------------------------------------------------------------
def apply_hpz_mesh(mesh_config, zero_config, n_devices: int) -> None:
    """Split the data axis so params shard over groups of
    ``zero_hpz_partition_size`` (inner ``data``) and replicate across groups
    (``data_outer``); the partitioner keeps master/grads on the full DP world
    (``ZeroPartitioner`` hpZ branch)."""
    hpz = int(zero_config.zero_hpz_partition_size or 1)
    if hpz <= 1:
        return
    if zero_config.mics_shard_size and zero_config.mics_shard_size > 0:
        raise ValueError(
            "zero_hpz_partition_size and mics_shard_size both split the data "
            "axis and cannot be combined"
        )
    from deepspeed_tpu.runtime.config import split_data_axis

    split_data_axis(mesh_config, hpz, n_devices, "zero_hpz_partition_size")
