"""Quantize-on-load for legacy sharded checkpoints.

Counterpart of the reference's ``deepspeed/runtime/weight_quantizer.py``
(``WeightQuantization``): group-wise symmetric int8/int-N quantization
applied WHILE merging/splitting Megatron checkpoint shards, so the full
fp16/fp32 weights never need to be resident at once. numpy end to end —
this runs on the host during checkpoint load, before anything is placed on
device; the dequantize ride-along (scales) feeds the int8 inference path.

Scale convention matches the reference: ``quantize_data`` stores
``s = 2^bits / (2*max + 1e-5)`` per group and the merged scale tensors hold
``1/s`` (the dequant multiplier).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

__all__ = ["WeightQuantization", "dequantize_weight"]


class WeightQuantization:
    """(reference weight_quantizer.py:11)"""

    def __init__(self, mlp_extra_grouping: bool = True, mp_size: int = 1):
        self.dense_scales: List[np.ndarray] = []
        self.qkv_scales: List[np.ndarray] = []
        self.mlp4hh_scales: List[np.ndarray] = []
        self.mlph4h_scales: List[np.ndarray] = []
        self.mlp_extra_grouping = mlp_extra_grouping
        self.mp_size = int(mp_size)

    def quantize_data(
        self, data: np.ndarray, quantize_bits: int, groups: int, key: Optional[str] = None  # noqa: ARG002
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Group-symmetric fake-int quantization (reference :21): returns
        (int8 data, per-group scale s) with q = clip(round(x*s))."""
        flat = np.asarray(data, np.float32).reshape(-1)
        if flat.size % groups != 0:
            groups = 1
        grouped = flat.reshape(groups, -1)
        max_d = np.maximum(grouped.max(axis=1), np.abs(grouped.min(axis=1)))
        scale = (1 << quantize_bits) / (2.0 * max_d + 1e-5)
        lo = -(1 << (quantize_bits - 1))
        hi = (1 << (quantize_bits - 1)) - 1
        q = np.clip(np.round(grouped * scale[:, None]), lo, hi)
        return q.reshape(np.shape(data)).astype(np.int8), scale.astype(np.float32)

    def is_mlp(self, data: np.ndarray, merge_count: int = 1) -> bool:
        return (
            (self.mp_size * data.shape[0] * merge_count) / data.shape[1] == 4
            or (self.mp_size * data.shape[1] * merge_count) / data.shape[0] == 4
        )

    def is_qkv(self, data: np.ndarray) -> bool:
        return (
            (self.mp_size * data.shape[0]) / data.shape[1] == 3
            or (self.mp_size * data.shape[1]) / data.shape[0] == 3
        )

    def Quantize(
        self,
        value_list: List[np.ndarray],
        quantize_bits: int,
        groups: int,
        key: str,
        merge_dim: int = 0,
    ) -> List[np.ndarray]:
        """Quantize each shard, recording the merged 1/s dequant scales per
        weight family (reference :42)."""
        if self.mlp_extra_grouping and self.is_mlp(value_list[0], merge_count=len(value_list)):
            groups *= 2
        q_scale = []
        out = []
        for data in value_list:
            data_int, data_scale = self.quantize_data(data, quantize_bits, groups, key)
            q_scale.append(data_scale.reshape(1, -1))
            out.append(data_int)
        q_scale = 1.0 / np.concatenate(q_scale, axis=merge_dim).reshape(-1)[None, :]
        if "mlp.dense_4h_to_h.weight" in key:
            self.mlp4hh_scales.append(q_scale)
        elif "mlp.dense_h_to_4h.weight" in key:
            self.mlph4h_scales.append(q_scale)
        elif "attention.query_key_value.weight" in key:
            self.qkv_scales.append(q_scale)
        else:
            self.dense_scales.append(q_scale)
        return out

    def merge_layer_scales(self, layer_scales: List[np.ndarray]) -> np.ndarray:
        max_dim = max(s.shape[-1] for s in layer_scales)
        padded = [
            np.concatenate([s, np.zeros((1, max_dim - s.shape[-1]), np.float32)], axis=-1)
            if s.shape[-1] < max_dim
            else s
            for s in layer_scales
        ]
        return np.concatenate(padded)[None, ...]

    def merge_scales(self) -> np.ndarray:
        """Per-layer [qkv, dense, h4h, 4hh] scale stack (reference :72)."""
        all_scales = [
            self.merge_layer_scales([qkv, dense, mh4h, m4hh])
            for dense, qkv, m4hh, mh4h in zip(
                self.dense_scales, self.qkv_scales, self.mlp4hh_scales, self.mlph4h_scales
            )
        ]
        return np.concatenate(all_scales)

    def merge_scales_split(self, split_count: int) -> List[np.ndarray]:
        """Scales regrouped per target split rank (reference :79)."""
        all_scales: List[List[np.ndarray]] = [[] for _ in range(split_count)]
        for dense, qkv, m4hh, mh4h in zip(
            self.dense_scales, self.qkv_scales, self.mlp4hh_scales, self.mlph4h_scales
        ):
            dense_s = np.split(dense.reshape(-1), split_count)
            qkv_s = np.split(qkv.reshape(-1), split_count)
            m4hh_s = np.split(m4hh.reshape(-1), split_count)
            mh4h_s = np.split(mh4h.reshape(-1), split_count)
            for i in range(split_count):
                all_scales[i].append(
                    self.merge_layer_scales(
                        [s[None, :] for s in (qkv_s[i], dense_s[i], mh4h_s[i], m4hh_s[i])]
                    )
                )
        return [np.concatenate(s) for s in all_scales]

    def sd_quantize_megatron(self, sd, quantize_bits: int, groups: int):
        """Quantize a whole (already-merged) Megatron module dict in place
        (reference :98): the four transformer matmul families."""
        keys = sd.keys()
        for key in keys:
            value_list = [np.asarray(sd[key])]
            if (
                "attention.dense.weight" in key
                or "mlp.dense_4h_to_h.weight" in key
                or "mlp.dense_h_to_4h.weight" in key
                or "attention.query_key_value.weight" in key
            ):
                value_list = self.Quantize(value_list, quantize_bits, groups, key=key)
            sd[key] = value_list[0]
        return sd, self.merge_scales()


def dequantize_weight(q: np.ndarray, scale: np.ndarray, groups: int) -> np.ndarray:
    """Invert ``quantize_data``: ``x ≈ q / s`` given the RAW per-group scale
    ``s`` it returned. The merged scale tensors (``merge_scales``) store the
    reciprocal ``1/s`` — invert before passing those here."""
    flat = np.asarray(q, np.float32).reshape(-1)
    if flat.size % groups != 0:
        groups = 1
    grouped = flat.reshape(groups, -1)
    s = np.asarray(scale, np.float32).reshape(-1)[:groups]
    return (grouped / s[:, None]).reshape(q.shape)
