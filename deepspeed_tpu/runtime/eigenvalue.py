"""Hessian eigenvalue estimation (MoQ precision switching).

Counterpart of the reference's ``Eigenvalue`` (``deepspeed/runtime/eigenvalue.py``,
engine hook engine.py:2103-2116): power iteration estimating the largest
eigenvalue of the loss Hessian per parameter block; MoQ uses the trajectory
to decide when to drop quantization precision.

JAX makes the Hessian-vector product exact and cheap:
``jax.jvp(jax.grad(loss), (p,), (v,))`` — no double-backward plumbing.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp


class Eigenvalue:
    def __init__(
        self,
        verbose: bool = False,
        max_iter: int = 100,
        tol: float = 1e-2,
        stability: float = 1e-6,
        gas_boundary_resolution: int = 1,
        layer_name: str = "",
        layer_num: int = 0,
    ):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def nan_to_zero(self, x):
        return jnp.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0)

    def compute_eigenvalue(
        self,
        loss_fn: Callable[[Any], jnp.ndarray],
        params: Any,
        rng: Optional[jax.Array] = None,
    ) -> float:
        """Largest |eigenvalue| of the Hessian of ``loss_fn`` at ``params``
        via power iteration with exact hvps."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        grad_fn = jax.grad(loss_fn)

        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = treedef.unflatten(
            [jax.random.normal(k, l.shape, jnp.float32) for k, l in zip(keys, leaves)]
        )

        def normalize(t):
            sq = sum(jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(t))
            norm = jnp.sqrt(sq) + self.stability
            return jax.tree_util.tree_map(lambda x: x / norm, t), norm

        v, _ = normalize(v)
        eig = jnp.float32(0.0)

        @jax.jit
        def hvp(p, vec):
            _, out = jax.jvp(grad_fn, (p,), (vec,))
            return jax.tree_util.tree_map(self.nan_to_zero, out)

        prev = None
        for i in range(self.max_iter):
            hv = hvp(params, v)
            # Rayleigh quotient v·Hv (v normalized)
            eig = sum(
                jnp.sum(a * b)
                for a, b in zip(jax.tree_util.tree_leaves(v), jax.tree_util.tree_leaves(hv))
            )
            v, norm = normalize(hv)
            e = float(jax.device_get(eig))
            if prev is not None and abs(prev) > 0 and abs(e - prev) / abs(prev) < self.tol:
                break
            prev = e
        return abs(float(jax.device_get(eig)))

    def compute_eigenvalue_per_block(
        self,
        loss_fn: Callable[[Any], jnp.ndarray],
        params: Dict[str, Any],
        block_keys: Optional[List[str]] = None,
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, float]:
        """Per-block eigenvalues (the reference iterates model layers): each
        block's Hessian is w.r.t. that sub-tree with the rest frozen."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        keys = block_keys or list(params.keys())
        out = {}
        for k in keys:
            def block_loss(block, k=k):
                merged = dict(params)
                merged[k] = block
                return loss_fn(merged)

            rng, sub = jax.random.split(rng)
            out[k] = self.compute_eigenvalue(block_loss, params[k], rng=sub)
        return out
