"""Hybrid engine (RLHF / DS-Chat).

Counterpart of the reference's ``DeepSpeedHybridEngine``
(``deepspeed/runtime/hybrid_engine.py:32``): one engine that flips between
ZeRO training mode and inference mode over the *same* weights for
generate-then-train loops. On TPU the flip is free — the live (sharded) bf16
param tree is passed to a jitted eval/generate program; no gather/re-partition
dance is needed because both programs read the same sharded buffers.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedHybridEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._in_inference_mode = False
        self._generate_jit = None
        cfg = self._config.hybrid_engine
        self.max_out_tokens = cfg.max_out_tokens
        # LoRA (reference hybrid_engine.py:69,138-157 fuse/unfuse around
        # rollouts; containers/features/hybrid_engine.py:50-80)
        self._lora = None
        self._lora_scaling = 1.0
        self._prefuse_params = None
        self._fused_cache = None
        self.is_lora_fused = False
        self._jit_fuse = None
        log_dist(f"HybridEngine: max_out_tokens={self.max_out_tokens}", ranks=[0])

    def eval(self):
        self._in_inference_mode = True
        return super().eval()

    def train(self, mode: bool = True):
        self._in_inference_mode = not mode
        if mode and self.is_lora_fused:
            # the reference unfuses before training for the same reason:
            # training math must see the base weights
            self.unfuse_lora_weight()
        return super().train(mode)

    # ------------------------------------------------------------------
    # LoRA (DS-Chat RLHF adapters)
    # ------------------------------------------------------------------
    def set_lora(self, lora_params, scaling: float = 1.0) -> None:
        """Attach adapter state (reference ``set_lora_params``): a pytree
        from ``module_inject.lora.init_lora_params`` (or the same shape).
        Rollouts then read ``W + scaling * right @ left`` views; training
        weights are untouched."""
        self._lora = lora_params
        self._lora_scaling = float(scaling)
        self._fused_cache = None

    def configure_lora(self, rank: int = 8, alpha: float = 16.0, target_keys=None, rng=None):
        """Create fresh adapters over the live params and attach them."""
        from deepspeed_tpu.module_inject.lora import (
            DEFAULT_TARGET_KEYS,
            LoRAConfig,
            init_lora_params,
        )

        if not self._initialized:
            raise RuntimeError("configure_lora before engine state is initialized")
        cfg = LoRAConfig(
            rank=rank, alpha=alpha, target_keys=tuple(target_keys or DEFAULT_TARGET_KEYS)
        )
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        lora = init_lora_params(self.get_params(), cfg, rng)
        self.set_lora(lora, cfg.scaling)
        return lora

    def _fused_view(self, params):
        from deepspeed_tpu.module_inject.lora import fuse_lora_tree

        # memoize on the source tree's identity: params change every step
        # (new arrays from the jitted step), so id() is a safe cache key —
        # rollout loops between steps reuse one fuse instead of paying the
        # einsum+copy per generate() call
        cached = self._fused_cache
        if cached is not None and cached[0] is params and cached[1] is self._lora:
            return cached[2]
        if self._jit_fuse is None:
            self._jit_fuse = jax.jit(fuse_lora_tree, static_argnums=(2,))
        view = self._jit_fuse(params, self._lora, self._lora_scaling)
        self._fused_cache = (params, self._lora, view)
        return view

    def fuse_lora_weight(self) -> None:
        """Swap the compute-dtype store to the fused view (reference
        ``fuse_lora_weight`` hybrid_engine.py:141). Pure-functional fuse:
        the pre-fuse tree is stashed, so unfusing is EXACT — no bf16
        add/subtract drift like the reference's in-place mutation."""
        if self._lora is None or self.is_lora_fused:
            return
        if not self._initialized or self._params is None:
            raise RuntimeError("fuse_lora_weight before engine state is initialized")
        self._prefuse_params = self._params
        self._params = self._fused_view(self._params)
        self.is_lora_fused = True

    def unfuse_lora_weight(self) -> None:
        """Exact restore of the pre-fuse weights (reference
        ``unfuse_lora_weight`` hybrid_engine.py:148)."""
        if not self.is_lora_fused:
            return
        self._params = self._prefuse_params
        self._prefuse_params = None
        # drop the fused view now: training resumes after a rollout, and a
        # retained cache would pin a full compute-dtype param copy (plus the
        # since-donated base tree it keys on) in HBM across training steps
        self._fused_cache = None
        self.is_lora_fused = False

    def forward(self, batch):
        if self._training_mode and self.is_lora_fused:
            self.unfuse_lora_weight()
        return super().forward(batch)

    def save_checkpoint(self, *args, **kwargs):
        # never persist fused weights: the module state would bake the
        # adapter delta into the base (and diverge from the fp32 master)
        if self.is_lora_fused:
            log_dist("save_checkpoint: unfusing LoRA before saving", ranks=[0])
            self.unfuse_lora_weight()
        return super().save_checkpoint(*args, **kwargs)

    def load_checkpoint(self, *args, **kwargs):
        # drop any fuse state: the stash predates the load, and the loaded
        # weights are unfused by construction (see save_checkpoint)
        if self.is_lora_fused:
            self.unfuse_lora_weight()
        self._fused_cache = None
        return super().load_checkpoint(*args, **kwargs)

    def generate(
        self,
        input_ids,
        max_new_tokens: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
    ):
        """Rollout with the CURRENT training weights (the RLHF actor step).

        ``TransformerLM``-layout modules take the KV-cached path
        (``inference/decode.py``): one jitted prefill + one jitted on-device
        decode loop over the live sharded params — the fast cached rollout
        that is the reference hybrid engine's whole point
        (``deepspeed/runtime/hybrid_engine.py:32``, kernel-injected
        inference inside training). Other modules fall back to the
        full-forward-per-token program. Both support greedy and
        temperature/top-k/top-p sampling."""
        if not self._initialized:
            self.init_params(jnp.asarray(input_ids))
        max_new = max_new_tokens or self.max_out_tokens
        module = self.module
        self._rng, sub = jax.random.split(self._rng)

        # adapters attached but not fused: roll out on a fused VIEW without
        # touching engine state (fuse is pure, so this is free to discard)
        rollout_params = self._params
        if self._lora is not None and not self.is_lora_fused:
            rollout_params = self._fused_view(self._params)

        from deepspeed_tpu.models.transformer import TransformerLM

        if isinstance(module, TransformerLM) and self._params is not None:
            from deepspeed_tpu.inference.decode import generate as kv_generate

            leaf = jax.tree_util.tree_leaves(rollout_params["embed"])[0]
            return kv_generate(
                module.config,
                rollout_params,
                input_ids,
                max_new,
                eos_token_id=eos_token_id,
                temperature=temperature,
                rng=sub,
                top_k=top_k,
                top_p=top_p,
                pad_token_id=pad_token_id,
                dtype=leaf.dtype,  # cache in the live compute dtype
            )

        from deepspeed_tpu.inference.generation import greedy_generate

        def apply_fn(params, tokens, rng):
            return module.apply(params, tokens, rngs={"dropout": rng}, train=False)

        if self._generate_jit is None:
            self._generate_jit = {}
        return greedy_generate(
            apply_fn,
            rollout_params,
            input_ids,
            max_new,
            sub,
            eos_token_id=eos_token_id,
            pad_token_id=pad_token_id,
            jit_cache=self._generate_jit,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
        )
