"""Hybrid engine (RLHF / DS-Chat).

Counterpart of the reference's ``DeepSpeedHybridEngine``
(``deepspeed/runtime/hybrid_engine.py:32``): one engine that flips between
ZeRO training mode and inference mode over the *same* weights for
generate-then-train loops. On TPU the flip is free — the live (sharded) bf16
param tree is passed to a jitted eval/generate program; no gather/re-partition
dance is needed because both programs read the same sharded buffers.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedHybridEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._in_inference_mode = False
        self._generate_jit = None
        cfg = self._config.hybrid_engine
        self.max_out_tokens = cfg.max_out_tokens
        log_dist(f"HybridEngine: max_out_tokens={self.max_out_tokens}", ranks=[0])

    def eval(self):
        self._in_inference_mode = True
        return super().eval()

    def train(self, mode: bool = True):
        self._in_inference_mode = not mode
        return super().train(mode)

    def generate(
        self,
        input_ids,
        max_new_tokens: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
    ):
        """Rollout with the CURRENT training weights (the RLHF actor step).

        ``TransformerLM``-layout modules take the KV-cached path
        (``inference/decode.py``): one jitted prefill + one jitted on-device
        decode loop over the live sharded params — the fast cached rollout
        that is the reference hybrid engine's whole point
        (``deepspeed/runtime/hybrid_engine.py:32``, kernel-injected
        inference inside training). Other modules fall back to the
        full-forward-per-token program. Both support greedy and
        temperature/top-k/top-p sampling."""
        if not self._initialized:
            self.init_params(jnp.asarray(input_ids))
        max_new = max_new_tokens or self.max_out_tokens
        module = self.module
        self._rng, sub = jax.random.split(self._rng)

        from deepspeed_tpu.models.transformer import TransformerLM

        if isinstance(module, TransformerLM) and self._params is not None:
            from deepspeed_tpu.inference.decode import generate as kv_generate

            leaf = jax.tree_util.tree_leaves(self._params["embed"])[0]
            return kv_generate(
                module.config,
                self._params,
                input_ids,
                max_new,
                eos_token_id=eos_token_id,
                temperature=temperature,
                rng=sub,
                top_k=top_k,
                top_p=top_p,
                pad_token_id=pad_token_id,
                dtype=leaf.dtype,  # cache in the live compute dtype
            )

        from deepspeed_tpu.inference.generation import greedy_generate

        def apply_fn(params, tokens, rng):
            return module.apply(params, tokens, rngs={"dropout": rng}, train=False)

        if self._generate_jit is None:
            self._generate_jit = {}
        return greedy_generate(
            apply_fn,
            self._params,
            input_ids,
            max_new,
            sub,
            eos_token_id=eos_token_id,
            pad_token_id=pad_token_id,
            jit_cache=self._generate_jit,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
        )
