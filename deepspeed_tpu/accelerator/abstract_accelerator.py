"""Accelerator abstraction.

TPU-native counterpart of the reference's ``accelerator/abstract_accelerator.py:10``
(``DeepSpeedAccelerator`` ABC, ~60 methods). The surface is preserved where it is
meaningful on XLA devices; CUDA-stream notions map onto JAX's async dispatch
(streams are no-ops that preserve the call protocol), and op-builder dispatch
resolves Pallas/XLA-backed builders instead of nvcc extensions.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Optional


class DeepSpeedAccelerator(abc.ABC):
    def __init__(self):
        self._name: str = ""
        self._communication_backend_name: str = ""

    # --- device APIs ---------------------------------------------------
    @abc.abstractmethod
    def is_synchronized_device(self) -> bool:
        ...

    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str:
        ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def set_device(self, device_index: int) -> None:
        ...

    @abc.abstractmethod
    def current_device(self) -> int:
        ...

    @abc.abstractmethod
    def current_device_name(self) -> str:
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None:
        ...

    # --- RNG APIs ------------------------------------------------------
    @abc.abstractmethod
    def random(self):
        ...

    @abc.abstractmethod
    def set_rng_state(self, new_state, device_index: Optional[int] = None) -> None:
        ...

    @abc.abstractmethod
    def get_rng_state(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def manual_seed(self, seed: int) -> None:
        ...

    @abc.abstractmethod
    def initial_seed(self) -> int:
        ...

    @abc.abstractmethod
    def default_generator(self, device_index: int):
        ...

    # --- streams / events ---------------------------------------------
    @abc.abstractmethod
    def Stream(self, *args, **kwargs):
        ...

    @abc.abstractmethod
    def stream(self, stream):
        ...

    @abc.abstractmethod
    def current_stream(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def default_stream(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def Event(self, **kwargs):
        ...

    # --- memory management ---------------------------------------------
    @abc.abstractmethod
    def empty_cache(self) -> None:
        ...

    @abc.abstractmethod
    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def reset_max_memory_allocated(self, device_index: Optional[int] = None) -> None:
        ...

    @abc.abstractmethod
    def memory_reserved(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def max_memory_reserved(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def total_memory(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def available_memory(self, device_index: Optional[int] = None) -> int:
        ...

    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        return {}

    # deprecated torch aliases kept for reference API parity
    # (abstract_accelerator.py memory_cached family + manual_seed_all)
    def memory_cached(self, device_index: Optional[int] = None) -> int:
        return self.memory_reserved(device_index)

    def max_memory_cached(self, device_index: Optional[int] = None) -> int:
        return self.max_memory_reserved(device_index)

    def reset_max_memory_cached(self, device_index: Optional[int] = None) -> None:
        # the 'cached' family is the reserved family: reset the peak stats
        # (which cover reserved peaks) so the read/reset pair stays coherent
        self.reset_peak_memory_stats(device_index)

    def manual_seed_all(self, seed: int) -> None:
        self.manual_seed(seed)

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        pass

    # --- dtype support --------------------------------------------------
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def supported_dtypes(self) -> list:
        ...

    # --- misc ----------------------------------------------------------
    @abc.abstractmethod
    def amp(self):
        ...

    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    @abc.abstractmethod
    def range_push(self, msg: str):
        ...

    @abc.abstractmethod
    def range_pop(self):
        ...

    @abc.abstractmethod
    def lazy_call(self, callback):
        ...

    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        ...

    @abc.abstractmethod
    def is_triton_supported(self) -> bool:
        ...

    # --- graph capture (maps to jax.jit compilation cache) -------------
    @abc.abstractmethod
    def create_graph(self):
        ...

    @abc.abstractmethod
    def capture_to_graph(self, graph, pool=None, stream=None):
        ...

    @abc.abstractmethod
    def replay_graph(self, graph):
        ...

    # --- tensor/array namespace ops -------------------------------------
    @abc.abstractmethod
    def pin_memory(self, tensor, align_bytes: int = 1):
        ...

    @abc.abstractmethod
    def is_pinned(self, tensor) -> bool:
        ...

    @abc.abstractmethod
    def on_accelerator(self, tensor) -> bool:
        ...

    # --- op builder dispatch --------------------------------------------
    @abc.abstractmethod
    def op_builder_dir(self) -> str:
        ...

    @abc.abstractmethod
    def create_op_builder(self, op_name: str):
        ...

    @abc.abstractmethod
    def get_op_builder(self, op_name: str):
        ...

    @abc.abstractmethod
    def build_extension(self):
        ...

    @abc.abstractmethod
    def export_envs(self) -> list:
        ...
