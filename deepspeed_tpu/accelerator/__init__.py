from .abstract_accelerator import DeepSpeedAccelerator
from .real_accelerator import get_accelerator, set_accelerator, is_current_accelerator_supported

__all__ = [
    "DeepSpeedAccelerator",
    "get_accelerator",
    "set_accelerator",
    "is_current_accelerator_supported",
]
