"""Accelerator selection.

Counterpart of the reference's ``accelerator/real_accelerator.py:45-140``:
``get_accelerator()`` singleton honoring the ``DS_ACCELERATOR`` env var, else
probing the JAX backend (tpu/axon → TPU accelerator, otherwise CPU).
"""

from __future__ import annotations

import os
from typing import Optional

from .abstract_accelerator import DeepSpeedAccelerator

_accelerator: Optional[DeepSpeedAccelerator] = None

_TPU_PLATFORMS = ("tpu", "axon")


def _detect_platform() -> str:
    override = os.environ.get("DS_ACCELERATOR")
    if override:
        return override.lower()
    try:
        import jax

        platform = jax.devices()[0].platform
        return "tpu" if platform in _TPU_PLATFORMS else "cpu"
    except Exception:
        return "cpu"


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is None:
        name = _detect_platform()
        if name == "tpu":
            from .tpu_accelerator import TPU_Accelerator

            _accelerator = TPU_Accelerator()
        elif name == "cpu":
            from .cpu_accelerator import CPU_Accelerator

            _accelerator = CPU_Accelerator()
        else:
            raise ValueError(
                f"DS_ACCELERATOR={name!r} is not supported by the TPU build (expected 'tpu' or 'cpu')"
            )
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported() -> bool:
    return _detect_platform() in ("tpu", "cpu")
