"""CPU accelerator (JAX cpu platform) — used by the test harness.

Counterpart of the reference's ``accelerator/cpu_accelerator.py``. Identical to
the TPU accelerator except for naming: JAX's cpu platform runs the same XLA
programs, which is how the multi-chip sharding tests execute on a virtual
8-device CPU mesh.
"""

from __future__ import annotations

from typing import List, Optional

from .tpu_accelerator import TPU_Accelerator


class CPU_Accelerator(TPU_Accelerator):
    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla"

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "cpu"
        return f"cpu:{device_index}"

    def current_device_name(self) -> str:
        return f"cpu:{self._current_device_index}"

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def total_memory(self, device_index: Optional[int] = None) -> int:
        stats = self._memory_stats(device_index)
        if "bytes_limit" in stats:
            return int(stats["bytes_limit"])
        try:
            import psutil  # pragma: no cover - optional

            return int(psutil.virtual_memory().total)
        except Exception:
            return 0

    def export_envs(self) -> List[str]:
        return ["JAX", "XLA"]
