"""TPU implementation of the accelerator ABC.

Counterpart of the reference's ``accelerator/cuda_accelerator.py`` selected via
``accelerator/real_accelerator.py:45-140``. Device handles are ``jax.Device``
objects; memory stats come from the platform allocator
(``jax.Device.memory_stats()``); "streams" are thin objects that preserve the
call protocol — JAX dispatch is already asynchronous and ordered, so entering a
stream is a no-op and ``synchronize`` blocks on all outstanding work via
``jax.block_until_ready`` of a trivial computation barrier.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Dict, List, Optional

import numpy as np

from .abstract_accelerator import DeepSpeedAccelerator


class _NoOpStream:
    """Stream stand-in: XLA orders work for us; kept for API parity."""

    def __init__(self, device=None):
        self.device = device

    def synchronize(self):
        from deepspeed_tpu.utils.sync import device_sync

        device_sync()

    def wait_stream(self, other):  # noqa: ARG002
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _NoOpEvent:
    def __init__(self, enable_timing: bool = False, **kwargs):
        self.enable_timing = enable_timing
        self._time: Optional[float] = None

    def record(self, stream=None):  # noqa: ARG002
        import time

        self._time = time.perf_counter()

    def synchronize(self):
        pass

    def wait(self, stream=None):  # noqa: ARG002
        pass

    def query(self) -> bool:
        return True

    def elapsed_time(self, end_event: "_NoOpEvent") -> float:
        if self._time is None or end_event._time is None:
            return 0.0
        return (end_event._time - self._time) * 1000.0


class TPU_Accelerator(DeepSpeedAccelerator):
    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"
        self._current_device_index = 0
        self._seed = 42
        self._rng_key = None
        self._peak_marks = {}  # device_index -> peak watermark at last reset

    def _jax(self):
        import jax

        return jax

    def _devices(self):
        return self._jax().devices()

    # --- device APIs ---------------------------------------------------
    def is_synchronized_device(self) -> bool:
        return False

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index: Optional[int] = None):
        devs = self._devices()
        return devs[device_index if device_index is not None else self._current_device_index]

    def set_device(self, device_index: int) -> None:
        self._current_device_index = device_index

    def current_device(self) -> int:
        return self._current_device_index

    def current_device_name(self) -> str:
        return f"tpu:{self._current_device_index}"

    def device_count(self) -> int:
        return len(self._devices())

    def synchronize(self, device_index: Optional[int] = None) -> None:  # noqa: ARG002
        from deepspeed_tpu.utils.sync import device_sync

        device_sync()

    # --- RNG APIs ------------------------------------------------------
    def random(self):
        import jax

        return jax.random

    def _key(self):
        import jax

        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(self._seed)
        return self._rng_key

    def set_rng_state(self, new_state, device_index: Optional[int] = None) -> None:  # noqa: ARG002
        self._rng_key = new_state

    def get_rng_state(self, device_index: Optional[int] = None):  # noqa: ARG002
        return self._key()

    def manual_seed(self, seed: int) -> None:
        import jax

        self._seed = int(seed)
        self._rng_key = jax.random.PRNGKey(self._seed)

    def initial_seed(self) -> int:
        return self._seed

    def default_generator(self, device_index: int):  # noqa: ARG002
        return self._key()

    # --- streams / events ----------------------------------------------
    def Stream(self, *args, **kwargs):
        return _NoOpStream(*args, **kwargs)

    def stream(self, stream):
        return contextlib.nullcontext(stream)

    def current_stream(self, device_index: Optional[int] = None):
        return _NoOpStream(device_index)

    def default_stream(self, device_index: Optional[int] = None):
        return _NoOpStream(device_index)

    def Event(self, **kwargs):
        return _NoOpEvent(**kwargs)

    # --- memory management ---------------------------------------------
    def _memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        try:
            stats = self.device(device_index).memory_stats()
            return stats or {}
        except Exception:
            return {}

    def empty_cache(self) -> None:
        pass

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self._memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        """Peak since the last reset. The XLA allocator's peak_bytes_in_use
        is process-lifetime and cannot be cleared, so resets record a
        watermark: while the all-time peak hasn't moved past it, the current
        usage is the best available 'peak since reset'."""
        idx = device_index if device_index is not None else self._current_device_index
        stats = self._memory_stats(idx)
        peak = int(stats.get("peak_bytes_in_use", 0))
        mark = self._peak_marks.get(idx, 0)
        if peak > mark:
            return peak
        return int(stats.get("bytes_in_use", 0))

    def reset_max_memory_allocated(self, device_index: Optional[int] = None) -> None:
        idx = device_index if device_index is not None else self._current_device_index
        self._peak_marks[idx] = int(
            self._memory_stats(idx).get("peak_bytes_in_use", 0)
        )

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        self.reset_max_memory_allocated(device_index)

    def memory_reserved(self, device_index: Optional[int] = None) -> int:
        return int(self._memory_stats(device_index).get("bytes_reserved", self.memory_allocated(device_index)))

    def max_memory_reserved(self, device_index: Optional[int] = None) -> int:
        return self.max_memory_allocated(device_index)

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self._memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        return self.total_memory(device_index) - self.memory_allocated(device_index)

    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        return self._memory_stats(device_index)

    # --- dtype support --------------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # fp16 works on TPU but bf16 is the native fast path.
        return True

    def supported_dtypes(self) -> List[Any]:
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    # --- misc ----------------------------------------------------------
    def amp(self):
        return None

    def is_available(self) -> bool:
        try:
            return len(self._devices()) > 0
        except Exception:
            return False

    def range_push(self, msg: str):
        import jax.profiler

        ctx = jax.profiler.TraceAnnotation(msg)
        ctx.__enter__()
        self._range_stack = getattr(self, "_range_stack", [])
        self._range_stack.append(ctx)

    def range_pop(self):
        stack = getattr(self, "_range_stack", [])
        if stack:
            stack.pop().__exit__(None, None, None)

    def lazy_call(self, callback):
        callback()

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def is_triton_supported(self) -> bool:
        return False

    # --- graph capture: maps to jit compile cache -----------------------
    def create_graph(self):
        return None

    def capture_to_graph(self, graph, pool=None, stream=None):  # noqa: ARG002
        return contextlib.nullcontext()

    def replay_graph(self, graph):  # noqa: ARG002
        pass

    # --- host-memory ops -------------------------------------------------
    def pin_memory(self, tensor, align_bytes: int = 1):  # noqa: ARG002
        # numpy arrays on the TPU-VM host are DMA-able as-is.
        return np.ascontiguousarray(tensor) if isinstance(tensor, np.ndarray) else tensor

    def is_pinned(self, tensor) -> bool:  # noqa: ARG002
        return True

    def on_accelerator(self, tensor) -> bool:
        try:
            import jax

            if isinstance(tensor, jax.Array):
                return all(d.platform != "cpu" for d in tensor.devices())
        except Exception:
            pass
        return False

    # --- op builder dispatch ---------------------------------------------
    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops.op_builder"

    def create_op_builder(self, op_name: str):
        builder_cls = self.get_op_builder(op_name)
        return builder_cls() if builder_cls is not None else None

    def get_op_builder(self, op_name: str):
        from deepspeed_tpu.ops.op_builder import get_builder

        return get_builder(op_name)

    def build_extension(self):
        return None

    def export_envs(self) -> List[str]:
        return ["JAX", "XLA", "LIBTPU", "TPU"]
