"""Elasticity config (reference: ``deepspeed/elasticity/config.py``)."""

from __future__ import annotations

import json


class ElasticityError(Exception):
    """Base elasticity error."""


class ElasticityConfigError(ElasticityError):
    """Invalid elasticity config."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size incompatible with the elastic config."""


ELASTICITY = "elasticity"
ENABLED = "enabled"
ENABLED_DEFAULT = False
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000
MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]
MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000
NUM_GPUS_PER_NODE = "num_gpus_per_node"
NUM_GPUS_PER_NODE_DEFAULT = 1
MODEL_PARALLEL_SIZE = "model_parallel_size"
MODEL_PARALLEL_SIZE_DEFAULT = 1
MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0
VERSION = "version"
VERSION_DEFAULT = 0.2
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False
PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True


class ElasticityConfig:
    """Typed view of the ``elasticity`` config block::

        "elasticity": {
          "enabled": true,
          "max_train_batch_size": 2000,
          "micro_batch_sizes": [2,4,6],
          "min_gpus": 1, "max_gpus": 10000,
          "min_time": 20,
          "prefer_larger_batch": true,
          "version": 0.2
        }
    """

    def __init__(self, param_dict: dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if MAX_ACCEPTABLE_BATCH_SIZE in param_dict:
            self.max_acceptable_batch_size = param_dict[MAX_ACCEPTABLE_BATCH_SIZE]
        else:
            raise ElasticityConfigError(f"Elasticity config missing {MAX_ACCEPTABLE_BATCH_SIZE}")
        if MICRO_BATCHES in param_dict:
            self.micro_batches = param_dict[MICRO_BATCHES]
        else:
            raise ElasticityConfigError(f"Elasticity config missing {MICRO_BATCHES}")
        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"elasticity micro_batches must be a list, got {type(self.micro_batches)}"
            )
        if not all(map(lambda m: isinstance(m, int), self.micro_batches)):
            raise ElasticityConfigError(f"micro_batches must be integers: {self.micro_batches}")
        if not all(map(lambda m: m > 0, self.micro_batches)):
            raise ElasticityConfigError(f"micro_batches must be > 0: {self.micro_batches}")

        self.min_gpus = param_dict.get(MIN_GPUS, MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(MAX_GPUS, MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError("Elasticity min/max gpus must be > 0")
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError("Elasticity min_gpus cannot be greater than max_gpus")

        self.model_parallel_size = param_dict.get(MODEL_PARALLEL_SIZE, MODEL_PARALLEL_SIZE_DEFAULT)
        if self.model_parallel_size < 1:
            raise ElasticityConfigError("Model-Parallel size cannot be less than 1")
        self.num_gpus_per_node = param_dict.get(NUM_GPUS_PER_NODE, NUM_GPUS_PER_NODE_DEFAULT)
        if self.num_gpus_per_node < 1:
            raise ElasticityConfigError("Number of chips per node cannot be less than 1")

        self.min_time = param_dict.get(MIN_TIME, MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(f"Elasticity min time needs to be >= 0: given {self.min_time}")

        self.version = param_dict.get(VERSION, VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(PREFER_LARGER_BATCH, PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            IGNORE_NON_ELASTIC_BATCH_INFO, IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT
        )

    def repr(self) -> dict:
        return self.__dict__

    def __repr__(self) -> str:
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
