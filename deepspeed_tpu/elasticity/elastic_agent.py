"""Elastic agent (reference: ``deepspeed/elasticity/elastic_agent.py:28``
``DSElasticAgent`` extending torch-elastic's ``LocalElasticAgent``).

The reference's agent glues two things together: (a) the elasticity batch
math — a membership change must land on a world size whose schedule keeps
the global batch fixed — and (b) worker lifecycle: per-worker env assembly
and restart on resize. On TPU there is no torch-elastic; the agent drives
the launcher's per-host process model directly. Worker spawn/kill are
injectable so resize logic is testable without real processes (the
launcher passes subprocess-based implementations).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils.logging import log_dist, logger


class WorkerSpec:
    """Minimal spec (reference torch-elastic ``WorkerSpec`` surface)."""

    def __init__(
        self,
        entrypoint: List[str],
        local_world_size: int = 1,
        max_restarts: int = 100,
        master_addr: Optional[str] = None,
        master_port: int = 29500,
    ):
        self.entrypoint = list(entrypoint)
        self.local_world_size = local_world_size
        self.max_restarts = max_restarts
        self.master_addr = master_addr or "127.0.0.1"
        self.master_port = master_port


class DSElasticAgent:
    """Membership-aware launcher: recomputes the elastic schedule on every
    resize and restarts workers with the new (world, micro-batch, gas) env.

    ``spawn_fn(cmd, env) -> handle`` and ``kill_fn(handle)`` default to
    subprocess implementations; tests inject fakes.
    """

    def __init__(
        self,
        spec: WorkerSpec,
        ds_config: Dict[str, Any],
        env: Optional[Dict[str, str]] = None,
        spawn_fn: Optional[Callable] = None,
        kill_fn: Optional[Callable] = None,
    ):
        elastic = ds_config.get("elasticity", {})
        if not elastic.get("enabled", False):
            raise ValueError("DSElasticAgent requires elasticity.enabled in the config")
        self.spec = spec
        self.ds_config = ds_config
        self.ds_env = dict(env or {})
        self.restart_count = 0
        self._workers: List[Any] = []
        self.world_size = 0
        self._spawn = spawn_fn or self._default_spawn
        self._kill = kill_fn or self._default_kill

    # --- spawn/kill defaults -------------------------------------------
    @staticmethod
    def _default_spawn(cmd: List[str], env: Dict[str, str]):
        import subprocess

        return subprocess.Popen(cmd, env={**os.environ, **env})

    @staticmethod
    def _default_kill(handle) -> None:
        try:
            handle.terminate()
            handle.wait(timeout=30)
        except Exception:
            logger.warning("worker did not terminate cleanly")

    # --- schedule -------------------------------------------------------
    def schedule_for(self, world_size: int) -> Dict[str, int]:
        """(batch, micro, gas) for a world size; raises if the size is not in
        the elastic-compatible set (reference schedule recomputation)."""
        batch, valid, micro = compute_elastic_config(
            self.ds_config,
            target_deepspeed_version="0.10.2",
            world_size=world_size,
            return_microbatch=True,
        )
        gas = max(1, batch // max(1, micro * world_size))
        return {
            "train_batch_size": batch,
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": gas,
            "world_size": world_size,
        }

    def _worker_env(self, rank: int, world_size: int, sched: Dict[str, int]) -> Dict[str, str]:
        env = dict(self.ds_env)
        env.update(
            {
                "RANK": str(rank),
                "LOCAL_RANK": str(rank % self.spec.local_world_size),
                "WORLD_SIZE": str(world_size),
                "LOCAL_WORLD_SIZE": str(self.spec.local_world_size),
                "MASTER_ADDR": self.spec.master_addr,
                "MASTER_PORT": str(self.spec.master_port),
                "DS_ELASTIC_RESTART_COUNT": str(self.restart_count),
                "DS_ELASTIC_TRAIN_BATCH_SIZE": str(sched["train_batch_size"]),
                "DS_ELASTIC_MICRO_BATCH": str(sched["train_micro_batch_size_per_gpu"]),
                "DS_ELASTIC_GAS": str(sched["gradient_accumulation_steps"]),
            }
        )
        return env

    # --- lifecycle ------------------------------------------------------
    def start(self, world_size: int) -> Dict[str, int]:
        sched = self.schedule_for(world_size)
        for rank in range(world_size):
            env = self._worker_env(rank, world_size, sched)
            self._workers.append(self._spawn(self.spec.entrypoint, env))
        self.world_size = world_size
        log_dist(
            f"DSElasticAgent: started {world_size} workers "
            f"(batch {sched['train_batch_size']} = micro {sched['train_micro_batch_size_per_gpu']} "
            f"x gas {sched['gradient_accumulation_steps']} x {world_size})",
            ranks=[0],
        )
        return sched

    def on_membership_change(self, new_world_size: int) -> Dict[str, int]:
        """Resize: validate the new world against the elastic set FIRST
        (an invalid size must not kill the running job), then restart every
        worker with the recomputed schedule (checkpoint-resume is the
        workers' job, as in the reference)."""
        if new_world_size == self.world_size:
            return self.schedule_for(self.world_size)
        if self.restart_count >= self.spec.max_restarts:
            raise RuntimeError(f"exceeded max_restarts={self.spec.max_restarts}")
        sched = self.schedule_for(new_world_size)  # raises on invalid size
        self.stop()
        self.restart_count += 1
        for rank in range(new_world_size):
            env = self._worker_env(rank, new_world_size, sched)
            self._workers.append(self._spawn(self.spec.entrypoint, env))
        self.world_size = new_world_size
        log_dist(
            f"DSElasticAgent: resized to {new_world_size} workers "
            f"(restart {self.restart_count})",
            ranks=[0],
        )
        return sched

    def stop(self) -> None:
        for h in self._workers:
            self._kill(h)
        self._workers = []
