"""Elastic batch-size math.

Counterpart of ``deepspeed/elasticity/elasticity.py``: given an acceptable
max global batch, candidate micro-batch sizes, and chip-count bounds, find
the (global batch, chip counts) combinations that keep the global batch
FIXED as nodes join/leave — so training hyperparameters stay valid across
resizes. v0.1 (`_get_compatible_gpus_v01` reference :83) ignores model
parallelism; v0.2 (reference :126) requires chip counts divisible by
mp_size × chips_per_node.

All math is device-agnostic and applies to TPU slices unchanged (a "gpu"
here is a chip).
"""

from __future__ import annotations

from typing import List, Tuple

from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    ELASTICITY,
    ENABLED,
    ENABLED_DEFAULT,
    IGNORE_NON_ELASTIC_BATCH_INFO,
    IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT,
    MODEL_PARALLEL_SIZE,
    MODEL_PARALLEL_SIZE_DEFAULT,
    NUM_GPUS_PER_NODE,
    NUM_GPUS_PER_NODE_DEFAULT,
)

# accept any framework version >= this for elastic checkpoints
MINIMUM_DEEPSPEED_VERSION = "0.0.1"
LATEST_ELASTICITY_VERSION = 0.2


def _all_divisors(n: int) -> List[int]:
    out = []
    i = 1
    while i * i <= n:
        if n % i == 0:
            out.append(i)
            if i != n // i:
                out.append(n // i)
        i += 1
    return sorted(out)


def get_candidate_batch_sizes(base_list: List[int], max_acceptable_batch_size: int) -> List[int]:
    """All batch sizes ≤ max that are (micro_batch × power-of-2) highly
    composite candidates (reference elasticity.py:48)."""
    candidate_batch_size = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidate_batch_size.add(base)
            continue
        value = max_acceptable_batch_size // base
        index = value.bit_length() - 1  # floor(log2(value))
        candidate_batch_size.add(base * (2**index))
    return sorted(candidate_batch_size)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """Chip counts g such that batch_size % (micro × g) == 0 for some micro
    (reference elasticity.py:64)."""
    valid_gpus = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_gpus = batch_size // micro_batch
        for div in _all_divisors(max_gpus):
            if min_valid_gpus <= div <= max_valid_gpus:
                valid_gpus.add(div)
    return sorted(valid_gpus)


def get_compatible_gpus_v01(
    micro_batches: List[int],
    max_acceptable_batch_size: int,
    min_gpus: int = 1,
    max_gpus: int = 10000,
    prefer_larger: bool = True,
) -> Tuple[int, List[int]]:
    """Pick the candidate batch size with the most valid chip counts
    (reference `_get_compatible_gpus_v01` :83)."""
    candidate_batch_sizes = get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size)
    final_batch_size = 0
    valid_gpus: List[int] = []
    for batch_size in candidate_batch_sizes:
        current_valid_gpus = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        if len(current_valid_gpus) > len(valid_gpus) or (
            len(current_valid_gpus) == len(valid_gpus)
            and prefer_larger
            and batch_size > final_batch_size
        ):
            valid_gpus = current_valid_gpus
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def get_compatible_gpus_v02(
    micro_batches: List[int],
    max_acceptable_batch_size: int,
    current_num_gpus: int,
    min_gpus: int = 1,
    max_gpus: int = 10000,
    prefer_larger: bool = True,
    num_gpus_per_node: int = 1,
    model_parallel_size: int = 1,
):
    """v0.2: model-parallel-aware (reference `_get_compatible_gpus_v02` :126).

    Works at NODE granularity: each node holds ``num_gpus_per_node //
    model_parallel_size`` data-parallel replicas, so chips per node must be
    divisible by mp_size, the v0.1 search runs over node counts, and results
    scale by dp_size_per_node. Returns (batch, valid dp world sizes,
    micro-batch for the current size); when the current size is not in the
    valid list, falls back to a batch built around the current dp size.
    """
    import math

    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityError(
            f"chips per node {num_gpus_per_node} must be divisible by "
            f"model_parallel_size {model_parallel_size}"
        )
    dp_size_per_node = num_gpus_per_node // model_parallel_size

    def _micro_for(batch: int):
        chosen = None
        for mb in micro_batches:
            if (batch // current_num_gpus) % mb == 0 and (
                chosen is None or (prefer_larger and mb > chosen)
            ):
                chosen = mb
        return chosen

    final_batch_size, valid_node_counts = get_compatible_gpus_v01(
        micro_batches,
        max_acceptable_batch_size=int(max_acceptable_batch_size / dp_size_per_node),
        min_gpus=int(min_gpus / num_gpus_per_node),
        max_gpus=int(max_gpus / num_gpus_per_node),
        prefer_larger=prefer_larger,
    )
    final_batch_size = int(final_batch_size) * dp_size_per_node
    valid_dp_world_sizes = [i * dp_size_per_node for i in valid_node_counts]
    if current_num_gpus // model_parallel_size in valid_dp_world_sizes:
        return final_batch_size, valid_dp_world_sizes, _micro_for(final_batch_size)

    # current world size incompatible with the node-level search — build the
    # closest batch
    # around the dp size we actually have (reference :172)
    current_dp_size = (current_num_gpus / num_gpus_per_node) * dp_size_per_node
    candidates = [
        math.floor(max_acceptable_batch_size / (mb * current_dp_size)) * mb * current_dp_size
        for mb in micro_batches
    ]
    candidate_batch = max(candidates) if prefer_larger else min(candidates)
    return int(candidate_batch), [int(current_dp_size)], _micro_for(int(candidate_batch))


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus, max_gpus, prefer_larger):
    final_batch_size = 0
    valid_gpus: List[int] = []
    for batch_size in candidate_batch_sizes:
        current_valid_gpus = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        if len(current_valid_gpus) > len(valid_gpus) or (
            len(current_valid_gpus) == len(valid_gpus)
            and prefer_larger
            and batch_size > final_batch_size
        ):
            valid_gpus = current_valid_gpus
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def elasticity_enabled(ds_config: dict) -> bool:
    if ELASTICITY not in ds_config:
        return False
    return ds_config[ELASTICITY].get(ENABLED, ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict: dict) -> None:
    """Elastic config in env must match runtime config (reference :181)."""
    import json
    import os

    DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"
    if DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        scheduler_elastic_config_dict = json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG])
        scheduler_elastic_config = ElasticityConfig(scheduler_elastic_config_dict)
        runtime_elastic_config = ElasticityConfig(runtime_elastic_config_dict)
        err_str = "Elastic config '{}={}' seen by scheduler does not match config passed to runtime '{}={}'"
        if runtime_elastic_config.max_acceptable_batch_size != scheduler_elastic_config.max_acceptable_batch_size:
            raise ElasticityConfigError(
                err_str.format(
                    "max_acceptable_batch_size",
                    scheduler_elastic_config.max_acceptable_batch_size,
                    "max_acceptable_batch_size",
                    runtime_elastic_config.max_acceptable_batch_size,
                )
            )
        if runtime_elastic_config.micro_batches != scheduler_elastic_config.micro_batches:
            raise ElasticityConfigError(
                err_str.format(
                    "micro_batches",
                    scheduler_elastic_config.micro_batches,
                    "micro_batches",
                    runtime_elastic_config.micro_batches,
                )
            )
        if runtime_elastic_config.version != scheduler_elastic_config.version:
            raise ElasticityConfigError(
                err_str.format(
                    "version", scheduler_elastic_config.version, "version", runtime_elastic_config.version
                )
            )
    else:
        os.environ[DEEPSPEED_ELASTICITY_CONFIG] = json.dumps(runtime_elastic_config_dict)


def compute_elastic_config(ds_config: dict, target_deepspeed_version: str, world_size: int = 0, return_microbatch: bool = False):
    """Core entry (reference `compute_elastic_config` :233): returns
    (final_batch_size, valid_gpus) — plus micro_batch when
    ``return_microbatch`` — and validates world_size when given.

    Reference-contract note: under v0.2 ``valid_gpus`` holds *data-parallel
    world sizes* (chips / mp), and the world_size validation and micro-batch
    divisibility both compare against that unit, exactly as the reference
    does (:350, :355). Callers using model parallelism pass world_size in
    dp units, matching the reference's logged "Valid World Size
    (GPUs / Model Parallel Size)" semantics.
    """
    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(f"'{ELASTICITY}' is missing from config json")
    elastic_config_dict = ds_config[ELASTICITY]
    if not elastic_config_dict.get(ENABLED, ENABLED_DEFAULT):
        raise ElasticityConfigError("Elasticity is not enabled in config json")
    elastic_config = ElasticityConfig(elastic_config_dict)
    model_parallel_size = elastic_config.model_parallel_size
    num_gpus_per_node = elastic_config.num_gpus_per_node

    if model_parallel_size > 1 and float(elastic_config.version) != 0.2:
        raise ElasticityConfigError(
            "Elasticity V{} does not support model-parallel training".format(elastic_config.version)
        )
    if float(elastic_config.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            "Attempting to run elasticity version {} but runtime only supports up "
            "to {}".format(elastic_config.version, LATEST_ELASTICITY_VERSION)
        )

    micro_batch = None
    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size,
        )
    elif float(elastic_config.version) == 0.2:
        if world_size != 0:
            current_num_gpus = world_size
        else:
            import os

            ws_env = os.environ.get("WORLD_SIZE")
            if ws_env is None or not ws_env.isnumeric() or int(ws_env) <= 0:
                raise ElasticityConfigError(
                    "Elasticity v0.2 needs a positive WORLD_SIZE to compute a "
                    "valid batch size; pass world_size= or set the WORLD_SIZE "
                    f"env var (currently {ws_env!r})"
                )
            current_num_gpus = int(ws_env)
        final_batch_size, valid_gpus, candidate_microbatch_size = get_compatible_gpus_v02(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            current_num_gpus=current_num_gpus,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size,
            num_gpus_per_node=num_gpus_per_node,
            model_parallel_size=model_parallel_size,
        )
        micro_batch = candidate_microbatch_size
    else:
        raise NotImplementedError(f"Unable to find elastic logic for version: {elastic_config.version}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not valid with the current list of "
                f"valid chip counts: {valid_gpus}"
            )
        # chosen micro batch: largest micro that divides batch/world evenly
        # (reference :355)
        candidates = [
            mb
            for mb in sorted(set(elastic_config.micro_batches), reverse=True)
            if (final_batch_size // world_size) % mb == 0
        ]
        if not candidates:
            raise ElasticityError(
                f"Unable to find divisible micro batch size: world_size={world_size}, "
                f"final_batch_size={final_batch_size}, micro_batches={elastic_config.micro_batches}"
            )
        micro_batch = candidates[0]
    if return_microbatch:
        return final_batch_size, valid_gpus, micro_batch
    return final_batch_size, valid_gpus
