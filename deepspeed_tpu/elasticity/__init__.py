"""Elastic training (reference: ``deepspeed/elasticity/``)."""

from deepspeed_tpu.elasticity.elasticity import (
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_compatible_gpus_v01,
    get_compatible_gpus_v02,
)
from deepspeed_tpu.elasticity.config import ElasticityConfig, ElasticityConfigError, ElasticityError
from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent, WorkerSpec
from deepspeed_tpu.elasticity.fleet_policy import (
    FleetResizePolicy,
    valid_fleet_sizes,
)
