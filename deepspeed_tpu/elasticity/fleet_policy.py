"""Elastic resize policy for the serving fleet.

The elasticity layer's original job was keeping the global batch valid as
training nodes join and leave; this module is the same idea turned on the
serving fleet (``inference/fleet.py``): treat replica count as an elastic,
fault-masked resource (ZeRO-Infinity's capacity framing, arXiv 2104.07857)
instead of a fixed topology. The quantization reuses the elastic batch
math verbatim — :func:`valid_fleet_sizes` runs
:func:`~deepspeed_tpu.elasticity.elasticity.get_valid_gpus` with replicas
as the "gpus" and a replica's slot capacity as the "micro batch", so a
fleet only ever resizes to counts whose aggregate slot capacity divides
the configured fleet slot budget (the serving analog of "the global batch
stays fixed across resizes").

:class:`FleetResizePolicy` is the WHEN: watermarks on backlog per replica
(queued + live requests), hysteresis via a resize cooldown so a bursty
heavy-tailed trace (the loadgen's Pareto arrivals) cannot flap the fleet,
and clamping to ``[min_replicas, max_replicas]`` ∩ ``valid_counts``. The
HOW — drain via migration, join via journal catch-up — is the router's
(``FleetRouter.autoscale_step`` executes a policy decision).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from deepspeed_tpu.elasticity.elasticity import get_valid_gpus


def valid_fleet_sizes(
    fleet_slot_budget: int,
    slots_per_replica: int,
    min_replicas: int = 1,
    max_replicas: int = 4096,
) -> List[int]:
    """Replica counts whose aggregate slot capacity divides the fleet slot
    budget — ``get_valid_gpus`` with replicas as chips and per-replica
    slots as the micro batch. E.g. a 32-slot budget over 4-slot replicas
    resizes through {1, 2, 4, 8}."""
    return get_valid_gpus(
        int(fleet_slot_budget), [int(slots_per_replica)],
        int(min_replicas), int(max_replicas),
    )


@dataclass
class FleetResizePolicy:
    """Watermark + hysteresis resize decisions for a serving fleet.

    ``target_backlog_per_replica`` is the load (queued + live requests)
    one replica should carry; the policy scales toward
    ``ceil(backlog / target)`` replicas, but only once the per-replica
    load crosses ``scale_up_at × target`` (growth) or falls below
    ``scale_down_at × target`` (shrink), and never more often than one
    resize per ``cooldown_steps`` scheduler steps. Candidate sizes are
    snapped to ``valid_counts`` (upward when growing, downward when
    shrinking) and clamped to ``[min_replicas, max_replicas]``."""

    min_replicas: int = 1
    max_replicas: int = 8
    target_backlog_per_replica: float = 4.0
    scale_up_at: float = 1.5
    scale_down_at: float = 0.5
    cooldown_steps: int = 8
    valid_counts: Optional[Sequence[int]] = None
    _last_resize_step: int = field(default=-(10**9), init=False, repr=False)

    def __post_init__(self):
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        if self.target_backlog_per_replica <= 0:
            raise ValueError("target_backlog_per_replica must be positive")
        if not self.scale_down_at < self.scale_up_at:
            raise ValueError(
                f"watermarks must satisfy scale_down_at < scale_up_at, got "
                f"{self.scale_down_at} vs {self.scale_up_at}"
            )
        counts = sorted(
            set(self.valid_counts)
            if self.valid_counts is not None
            else range(self.min_replicas, self.max_replicas + 1)
        )
        counts = [
            c for c in counts if self.min_replicas <= c <= self.max_replicas
        ]
        if not counts:
            raise ValueError(
                f"no valid replica count inside [{self.min_replicas}, "
                f"{self.max_replicas}]"
            )
        self.valid_counts = counts

    def _snap(self, want: int, up: bool) -> int:
        """Nearest valid count: the smallest valid ≥ want when growing
        (capacity promises are met), the largest valid ≤ want when
        shrinking (never shrink past the demand estimate)."""
        if up:
            bigger = [c for c in self.valid_counts if c >= want]
            return bigger[0] if bigger else self.valid_counts[-1]
        smaller = [c for c in self.valid_counts if c <= want]
        return smaller[-1] if smaller else self.valid_counts[0]

    def decide(self, backlog: float, n_active: int, step: int) -> int:
        """Target replica count for the current load. Returns ``n_active``
        (no resize) inside the hysteresis band or during the cooldown."""
        n_active = max(int(n_active), 1)
        per = backlog / n_active
        want = max(
            1, math.ceil(backlog / self.target_backlog_per_replica)
        )
        if per >= self.scale_up_at * self.target_backlog_per_replica:
            target = self._snap(max(want, n_active + 1), up=True)
        elif per <= self.scale_down_at * self.target_backlog_per_replica:
            target = self._snap(min(want, n_active - 1), up=False)
        else:
            return n_active
        target = min(max(target, self.min_replicas), self.max_replicas)
        if target == n_active:
            return n_active
        if step - self._last_resize_step < self.cooldown_steps:
            return n_active  # hysteresis: no flapping inside the cooldown
        self._last_resize_step = step
        return target
